"""Training-health monitor: in-graph vitals + rolling divergence checks.

The reference exposes training health only through what the user
fetches; production TF experience (Abadi et al.) is that grad-norm /
update-ratio style vitals plus cheap divergence heuristics catch most
runs that are ABOUT to NaN long before they do. Here the vitals are
appended as ordinary ops at `optimizer.minimize(..., health=True)`
time, so they ride the same compiled step:

  health_grad_norm    sqrt(sum_p ||grad_p||^2)   (pre-clip, fp32)
  health_param_norm   sqrt(sum_p ||p||^2)        (pre-update values)
  health_update_ratio lr * grad_norm / (param_norm + eps) — the
                      classic "how big is this step relative to the
                      weights" vital (exact for SGD, a proxy for
                      adaptive optimizers)

Cost model: the vars are NOT persistable, so trace._prune_ops drops
every health op from any step that does not fetch them — a run that
never fetches monitor.fetch_list compiles the identical module it
would have without the monitor (pinned by tests/test_diagnostics.py).

observe() feeds the fetched values into rolling windows, exports
telemetry gauges when telemetry is on, and fires warnings (loss spike,
exploding/vanishing gradients) through logging + the telemetry
registry + the flight recorder.
"""
import collections
import logging
import math

import numpy as np

from .. import unique_name

__all__ = ["HealthMonitor"]

_LOG = logging.getLogger("paddle_tpu.diagnostics")


def _scalar(v):
    return float(np.asarray(v).ravel()[0])


class HealthMonitor:
    """Built by `Optimizer.minimize(loss, health=True)` (then available
    as `optimizer.health_monitor`) or attached manually via
    HealthMonitor.attach(loss, params_grads)."""

    def __init__(self, loss_var, grad_norm_var, param_norm_var,
                 window=20, loss_spike_factor=4.0,
                 grad_explode_threshold=1e3, grad_explode_factor=10.0,
                 grad_vanish_threshold=1e-8):
        self.loss_var = loss_var
        self.grad_norm_var = grad_norm_var
        self.param_norm_var = param_norm_var
        self.update_ratio_var = None        # set once the LR var exists
        self.window = window
        self.loss_spike_factor = loss_spike_factor
        self.grad_explode_threshold = grad_explode_threshold
        self.grad_explode_factor = grad_explode_factor
        self.grad_vanish_threshold = grad_vanish_threshold
        self._losses = collections.deque(maxlen=window)
        self._gnorms = collections.deque(maxlen=window)
        self.steps_observed = 0
        self.warnings = []                  # [{kind, message, step}]

    # ------------------------------------------------- graph building
    @staticmethod
    def _norm_over(block, vars_, tag):
        """Append sqrt(sum_i ||v_i||^2) ops; returns the scalar var."""
        sq_vars = []
        for v in vars_:
            sq = block.create_var(
                name=unique_name.generate(f"health_{tag}_sq"),
                shape=[1], dtype="float32", stop_gradient=True)
            block.append_op("squared_l2_norm", {"X": [v]},
                            {"Out": [sq]}, {})
            sq_vars.append(sq)
        total = block.create_var(
            name=unique_name.generate(f"health_{tag}_sumsq"),
            shape=[1], dtype="float32", stop_gradient=True)
        block.append_op("sum", {"X": sq_vars}, {"Out": [total]}, {})
        norm = block.create_var(
            name=unique_name.generate(f"health_{tag}_norm"),
            shape=[1], dtype="float32", stop_gradient=True)
        block.append_op("sqrt", {"X": [total]}, {"Out": [norm]}, {})
        return norm

    @classmethod
    def attach(cls, loss, params_grads, **options):
        """Append the vitals ops for `params_grads` (call AFTER
        append_backward, BEFORE the update ops are appended, so the
        param norm reads pre-update values)."""
        if not params_grads:
            raise ValueError("health monitor needs at least one "
                             "(param, grad) pair")
        block = params_grads[0][0].block.program.global_block()
        grad_norm = cls._norm_over(
            block, [g for _, g in params_grads], "grad")
        param_norm = cls._norm_over(
            block, [p for p, _ in params_grads], "param")
        return cls(loss, grad_norm, param_norm, **options)

    def _append_update_ratio(self, lr_var):
        """lr * grad_norm / (param_norm + eps); called by minimize()
        once apply_gradients has created the LR var."""
        if lr_var is None or self.update_ratio_var is not None:
            return
        block = self.grad_norm_var.block
        num = block.create_var(
            name=unique_name.generate("health_upd_num"),
            shape=[1], dtype="float32", stop_gradient=True)
        block.append_op("elementwise_mul",
                        {"X": [self.grad_norm_var], "Y": [lr_var]},
                        {"Out": [num]}, {"axis": -1})
        den = block.create_var(
            name=unique_name.generate("health_upd_den"),
            shape=[1], dtype="float32", stop_gradient=True)
        block.append_op("scale", {"X": [self.param_norm_var]},
                        {"Out": [den]}, {"scale": 1.0, "bias": 1e-12})
        ratio = block.create_var(
            name=unique_name.generate("health_update_ratio"),
            shape=[1], dtype="float32", stop_gradient=True)
        block.append_op("elementwise_div", {"X": [num], "Y": [den]},
                        {"Out": [ratio]}, {"axis": -1})
        self.update_ratio_var = ratio

    # ------------------------------------------------------ observing
    @property
    def fetch_list(self):
        """Auxiliary fetches to append to Executor.run's fetch_list
        (the loss itself is usually already fetched)."""
        out = [self.grad_norm_var, self.param_norm_var]
        if self.update_ratio_var is not None:
            out.append(self.update_ratio_var)
        return out

    def observe_fetches(self, values, loss=None):
        """`values` = the run() results for self.fetch_list (same
        order); returns the warnings fired for this step."""
        values = list(values)
        grad_norm = _scalar(values[0])
        param_norm = _scalar(values[1]) if len(values) > 1 else None
        ratio = _scalar(values[2]) if len(values) > 2 else None
        return self.observe(loss=loss, grad_norm=grad_norm,
                            param_norm=param_norm, update_ratio=ratio)

    def _warn(self, kind, message):
        from .. import telemetry as _tm
        rec = {"kind": kind, "message": message,
               "step": self.steps_observed}
        self.warnings.append(rec)
        _LOG.warning("health: %s (step %d): %s", kind,
                     self.steps_observed, message)
        if _tm.enabled():
            _tm.counter("health.warnings").inc()
            _tm.counter(f"health.warning.{kind}").inc()
        from . import recorder as _rec
        r = _rec.active()
        if r is not None:
            r.event("health_warning", **rec)
        return rec

    def observe(self, loss=None, grad_norm=None, param_norm=None,
                update_ratio=None):
        """Feed one step's vitals; returns warnings fired this step."""
        from .. import telemetry as _tm
        self.steps_observed += 1
        fired = []
        if _tm.enabled():
            if loss is not None:
                _tm.gauge("health.loss").set(float(loss))
            if grad_norm is not None:
                _tm.gauge("health.grad_norm").set(float(grad_norm))
            if update_ratio is not None:
                _tm.gauge("health.update_ratio").set(
                    float(update_ratio))
        from . import recorder as _rec
        r = _rec.active()
        if r is not None:
            r.annotate(**{k: v for k, v in
                          dict(loss=loss, grad_norm=grad_norm,
                               update_ratio=update_ratio).items()
                          if v is not None})

        if loss is not None:
            loss = float(loss)
            if not math.isfinite(loss):
                fired.append(self._warn(
                    "nonfinite_loss", f"loss is {loss}"))
            elif len(self._losses) >= 5:
                med = sorted(self._losses)[len(self._losses) // 2]
                if abs(loss) > self.loss_spike_factor * max(
                        abs(med), 1e-12):
                    fired.append(self._warn(
                        "loss_spike",
                        f"loss {loss:.4g} is >{self.loss_spike_factor}"
                        f"x the rolling median {med:.4g}"))
            self._losses.append(loss)
        if grad_norm is not None:
            grad_norm = float(grad_norm)
            if not math.isfinite(grad_norm) \
                    or grad_norm > self.grad_explode_threshold:
                fired.append(self._warn(
                    "exploding_gradients",
                    f"global grad norm {grad_norm:.4g} exceeds "
                    f"{self.grad_explode_threshold:.4g}"))
            elif len(self._gnorms) >= 5:
                med = sorted(self._gnorms)[len(self._gnorms) // 2]
                if grad_norm > self.grad_explode_factor * max(med,
                                                              1e-30):
                    fired.append(self._warn(
                        "exploding_gradients",
                        f"global grad norm {grad_norm:.4g} is "
                        f">{self.grad_explode_factor}x the rolling "
                        f"median {med:.4g}"))
            self._gnorms.append(grad_norm)
            if len(self._gnorms) == self.window and all(
                    g < self.grad_vanish_threshold
                    for g in self._gnorms):
                fired.append(self._warn(
                    "vanishing_gradients",
                    f"global grad norm < "
                    f"{self.grad_vanish_threshold:g} for "
                    f"{self.window} consecutive steps"))
        return fired
