"""Crash flight recorder: a ring buffer of per-step records with a
JSON post-mortem dump on failure.

A diverging run used to leave nothing behind but a stack trace; with
`PADDLE_TPU_FLIGHT_RECORDER=<dir>` (or `=1` for ./flight_recorder) the
executor appends one small record per step (step index, loss when
fetchable, step wall time, compile events, program fingerprint) into a
fixed-size ring, and the last `capacity` records are dumped as JSON
when:

  - a NaN/Inf check trips (the executor dumps before raising
    NanInfError, attaching the NumericsReport),
  - an uncaught exception unwinds the process (sys.excepthook chain),
  - the process exits with records still in the ring (atexit — the
    black box always lands), or
  - a fatal signal kills the interpreter (faulthandler writes the
    C-level traceback to <dir>/flight_fault.log; the JSON ring from
    the previous dump/exit remains alongside it).

`tools/tpudoctor.py postmortem <dump.json>` pretty-prints a dump.
Overhead when the env var is unset: one cached None check per step.
"""
import atexit
import collections
import json
import os
import sys
import time
import traceback

__all__ = ["FlightRecorder", "active", "enable", "disable", "enabled"]

DEFAULT_CAPACITY = 256
_TRUTHY = ("1", "true", "on", "yes")
_FALSY = ("", "0", "false", "off", "no")

_RECORDER = None
_RESOLVED = False


class FlightRecorder:
    def __init__(self, out_dir, capacity=DEFAULT_CAPACITY):
        self.out_dir = out_dir
        self.capacity = capacity
        self.records = collections.deque(maxlen=capacity)
        self.events = collections.deque(maxlen=64)
        self.last_dump_path = None
        self.dump_count = 0
        self._start = time.time()
        self._hooks_installed = False
        self._fault_file = None

    # -------------------------------------------------------- recording
    def record(self, **fields):
        """Append one per-step record (executor hot path — keep cheap)."""
        fields.setdefault("t", round(time.time() - self._start, 4))
        self.records.append(fields)

    def annotate(self, **fields):
        """Merge fields into the most recent record (health vitals)."""
        if self.records:
            self.records[-1].update(fields)

    def event(self, kind, **fields):
        """Out-of-band event (compile, health warning, ...)."""
        e = dict(kind=kind, t=round(time.time() - self._start, 4))
        e.update(fields)
        self.events.append(e)

    # ---------------------------------------------------------- dumping
    def dump(self, path=None, reason="manual", report=None, error=None):
        """Write the ring as a JSON post-mortem; returns the path."""
        payload = {
            "version": 1,
            "reason": reason,
            "time": time.time(),
            "uptime_s": round(time.time() - self._start, 3),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "capacity": self.capacity,
            "records": list(self.records),
            "events": list(self.events),
        }
        if report is not None:
            payload["report"] = report.to_dict() \
                if hasattr(report, "to_dict") else report
        if error is not None:
            payload["error"] = str(error)
        if path is None:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir,
                                f"flight_{os.getpid()}.json")
        try:
            with open(path, "w") as f:
                json.dump(payload, f, indent=1, default=str)
        except OSError:
            return None     # a dying process must not die again here
        self.last_dump_path = path
        self.dump_count += 1
        return path

    # ------------------------------------------------------------ hooks
    def install(self):
        """atexit + excepthook chain + faulthandler (idempotent)."""
        if self._hooks_installed:
            return self
        self._hooks_installed = True
        atexit.register(_atexit_dump)
        prev_hook = sys.excepthook

        def hook(etype, value, tb):
            r = active()
            if r is not None:
                r.dump(reason="uncaught_exception",
                       error="".join(traceback.format_exception(
                           etype, value, tb))[-4000:])
            prev_hook(etype, value, tb)

        sys.excepthook = hook
        try:
            import faulthandler
            os.makedirs(self.out_dir, exist_ok=True)
            self._fault_file = open(
                os.path.join(self.out_dir, "flight_fault.log"), "w")
            faulthandler.enable(file=self._fault_file)
        except (OSError, ImportError, ValueError):
            pass
        return self


def _atexit_dump():
    r = _RECORDER
    if r is not None and r.records and r.dump_count == 0:
        r.dump(reason="atexit")


def _env_dir():
    val = (os.environ.get("PADDLE_TPU_FLIGHT_RECORDER") or "").strip()
    if val.lower() in _FALSY:
        return None
    if val.lower() in _TRUTHY:
        return os.path.join(os.getcwd(), "flight_recorder")
    return val


def active():
    """The process flight recorder, or None when disabled. Resolves the
    env gate once; `enable()`/`disable()` override it."""
    global _RECORDER, _RESOLVED
    if not _RESOLVED:
        _RESOLVED = True
        d = _env_dir()
        if d is not None:
            cap = int(os.environ.get(
                "PADDLE_TPU_FLIGHT_RECORDER_STEPS",
                str(DEFAULT_CAPACITY)))
            _RECORDER = FlightRecorder(d, capacity=cap).install()
    return _RECORDER


def enabled():
    return active() is not None


def enable(out_dir=None, capacity=DEFAULT_CAPACITY, install_hooks=True):
    """Programmatic enablement (tests / notebooks)."""
    global _RECORDER, _RESOLVED
    _RESOLVED = True
    _RECORDER = FlightRecorder(
        out_dir or os.path.join(os.getcwd(), "flight_recorder"),
        capacity=capacity)
    if install_hooks:
        _RECORDER.install()
    return _RECORDER


def disable():
    global _RECORDER, _RESOLVED
    _RESOLVED = True
    _RECORDER = None
