"""paddle_tpu.diagnostics — the training-numerics doctor (tpudoctor).

Three pillars on top of PR 2's telemetry plumbing:

  numerics / bisect   NaN/Inf culprit localization: when a finite
                      check trips (Executor.run(check_nan_inf=True) or
                      PADDLE_TPU_CHECK_NAN_INF=1), the traced program
                      is re-executed as op-prefix slices under a
                      binary search and the failure is pinned to one
                      op, raising NanInfError with a NumericsReport
                      (op type, block/op index, tensor stats, feed
                      fingerprint, fix hint).
  health              opt-in in-graph vitals appended at
                      optimizer.minimize(..., health=True) time:
                      global grad norm, param norm, update ratio —
                      plus rolling-window divergence heuristics.
  recorder            a crash flight recorder: per-step ring buffer
                      dumped as a JSON post-mortem on NaN, uncaught
                      exception, or exit; PADDLE_TPU_FLIGHT_RECORDER
                      gates it, tools/tpudoctor.py prints it.

Everything is off by default: with no env flags and no explicit
opt-in, Executor.run issues zero extra fetches, device work, or host
readbacks (pinned by tests/test_bench_contract.py).
"""
from .numerics import (TensorStats, tensor_stats, NumericsReport,
                       NanInfError, feed_fingerprint, fix_hint)
from .bisect import localize
from .health import HealthMonitor
from . import recorder
from .recorder import FlightRecorder

__all__ = ["TensorStats", "tensor_stats", "NumericsReport",
           "NanInfError", "feed_fingerprint", "fix_hint", "localize",
           "HealthMonitor", "FlightRecorder", "recorder",
           "check_nan_inf_requested", "status"]

import os as _os

_FALSY = ("", "0", "false", "off", "no")


def check_nan_inf_requested():
    """The PADDLE_TPU_CHECK_NAN_INF env gate; "all" additionally
    checks updated persistable state (params/optimizer accumulators),
    any other truthy value checks fetches + updated state too (the
    cheap fetches-only mode is spelled "fetches")."""
    val = (_os.environ.get("PADDLE_TPU_CHECK_NAN_INF") or "").strip()
    return val.lower() not in _FALSY


def check_mode():
    """"all" (fetches + updated persistables, the default) or
    "fetches"."""
    val = (_os.environ.get("PADDLE_TPU_CHECK_NAN_INF") or "").strip()
    return "fetches" if val.lower() == "fetches" else "all"


def status():
    """One-line status dict for CLIs (tpustat header, tpudoctor)."""
    return {
        "nan_check": check_nan_inf_requested(),
        "flight_recorder": recorder.enabled(),
        "flight_recorder_dir":
            recorder.active().out_dir if recorder.enabled() else None,
    }
