"""NaN/Inf culprit localization by re-execution under bisection.

The jitted step is one opaque XLA module; when its outputs go
non-finite, this module re-executes the SAME traced op list eagerly
(core.trace.exec_op, same pruning, same per-op PRNG folds, same
pre-step state) as op-prefix slices under a binary search, and returns
a NumericsReport naming the first op whose outputs go non-finite.

Three phases, mirroring where non-finiteness can originate:

  forward   P(k) = "any output of ops[:k] is non-finite" is monotone in
            k (env only grows, values never change), so a binary search
            over prefix length finds the exact first bad op.
  backward  forward values are finite but a gradient is not. S(j) =
            "grads of the loss w.r.t. the free inputs of the op suffix
            ops[j:] contain a non-finite value" is monotone in j under
            the standard propagation assumption (a bad gradient does
            not cancel to a finite one upstream); the boundary where
            S flips is the op whose BACKWARD first emits non-finite
            gradients from finite inputs.
  update    forward and gradients are finite: the optimizer tail is
            short, so it is replayed op-by-op (exact attribution; the
            stacked-adam fusion in trace.py is arithmetic-identical to
            this per-op replay modulo ~1 ULP).

Determinism caveat: re-execution folds the same (seed, step, op index)
PRNG keys the compiled step used, so dropout streams match; backends
whose RNG is not bit-stable across jit/eager can in principle fail to
reproduce, in which case localize() returns None and the caller falls
back to an unlocalized report.
"""
import logging

import numpy as np

from ..core.trace import (exec_op, _prune_ops, _find_backward,
                          _collect_sparse_deltas)
from ..core.framework import grad_var_name
from .numerics import (NumericsReport, tensor_stats, feed_fingerprint)

__all__ = ["localize"]

_LOG = logging.getLogger("paddle_tpu.diagnostics")


def _nonfinite(v):
    import jax.numpy as jnp
    dt = getattr(v, "dtype", None)
    if dt is None or not (jnp.issubdtype(dt, jnp.floating)
                          or jnp.issubdtype(dt, jnp.complexfloating)):
        return False
    return not bool(jnp.all(jnp.isfinite(v)))


def _float_names(names, env):
    import jax.numpy as jnp
    out = []
    for n in names:
        v = env.get(n)
        if v is not None and jnp.issubdtype(
                getattr(v, "dtype", np.dtype("O")), jnp.floating):
            out.append(n)
    return out


def _op_stats(op, env, which="inputs"):
    stats = []
    slots = op.inputs if which == "inputs" else op.outputs
    for slot, names in slots.items():
        for n in names:
            if n in env:
                stats.append(tensor_stats(env[n], f"{slot}:{n}"))
    return stats


class _Session:
    """One localization run: the frozen op list + base env + PRNG key,
    with prefix execution as the shared primitive."""

    def __init__(self, program, feed, persist, fetch_names, is_test,
                 place, seed, step):
        import jax
        import jax.numpy as jnp
        self.program = program
        self.block = program.global_block()
        all_ops = list(self.block.ops)
        self.orig_idx = {id(op): i for i, op in enumerate(all_ops)}
        self.ops = _prune_ops(program, all_ops, fetch_names)
        self.bi = _find_backward(self.ops)
        self.is_test = is_test
        self.place = place
        # mirror Executor.run's stepped(): key folded from (seed, step)
        self.base_key = jax.random.fold_in(
            jax.random.PRNGKey(seed), jnp.uint32(step))
        env = {}
        env.update(feed)
        env.update(persist)
        for dname, wname in _collect_sparse_deltas(program, self.ops):
            if wname in env:
                env[dname] = jnp.zeros((), env[wname].dtype)
        self.env0 = env
        self.meta = dict(feed_fingerprint=feed_fingerprint(feed),
                         step=step, program_version=program._version,
                         seed=seed)

    def report(self, phase, op=None, pruned_idx=None, **kw):
        kw.setdefault("op_type", op.type if op is not None else None)
        kw.setdefault("op_idx", self.orig_idx.get(id(op))
                      if op is not None else None)
        return NumericsReport(phase, pruned_idx=pruned_idx,
                              block_idx=self.block.idx, **self.meta,
                              **kw)

    def run_prefix(self, k, env=None):
        """env after executing ops[:k] (or extend a given env from its
        recorded length — callers pass disjoint ranges)."""
        env = dict(self.env0) if env is None else env
        start = env.pop("__len__", 0)
        for i in range(start, k):
            exec_op(env, self.ops[i], i, self.base_key, self.is_test,
                    self.place, self.block)
        env["__len__"] = k
        return env

    def bad_outputs(self, env, lo, hi):
        """Names of non-finite outputs of ops[lo:hi] present in env."""
        bad = []
        for i in range(lo, hi):
            for n in self.ops[i].output_names():
                if n in env and _nonfinite(env[n]):
                    bad.append(n)
        return bad

    # ------------------------------------------------- forward phase
    def forward_culprit(self, n_fwd):
        """Binary search the smallest prefix with a non-finite output;
        returns a report or None when the whole forward is clean."""
        env_full = self.run_prefix(n_fwd)
        if not self.bad_outputs(env_full, 0, n_fwd):
            return None
        lo, hi = 0, n_fwd        # P(lo)=False (inputs checked), P(hi)=True
        while hi - lo > 1:
            mid = (lo + hi) // 2
            env = self.run_prefix(mid)
            # outputs of ops[:lo] are known clean — check only (lo, mid]
            if self.bad_outputs(env, lo, mid):
                hi = mid
            else:
                lo = mid
        c = hi - 1
        op = self.ops[c]
        env_before = self.run_prefix(c)
        env_after = self.run_prefix(c + 1, env=dict(env_before,
                                                    __len__=c))
        bad = self.bad_outputs(env_after, c, c + 1)
        return self.report(
            "forward", op, pruned_idx=c,
            input_stats=_op_stats(op, env_before, "inputs"),
            output_stats=_op_stats(op, env_after, "outputs"),
            nonfinite_vars=bad,
            detail=f"first non-finite output after executing "
                   f"{c + 1}/{len(self.ops)} traced ops")

    # ------------------------------------------------ backward phase
    def _suffix_free_inputs(self, j, env):
        """Float vars the suffix ops[j:bi] reads but does not produce —
        the differentiation cut for S(j)."""
        produced = set()
        free = []
        seen = set()
        for op in self.ops[j:self.bi]:
            for n in op.input_names():
                if n not in produced and n not in seen:
                    seen.add(n)
                    free.append(n)
            produced.update(op.output_names())
        return _float_names(free, env)

    def _suffix_grads(self, j, loss_name):
        """Grads of the loss w.r.t. the free inputs of ops[j:bi]
        (None, {}) when the cut has nothing to differentiate."""
        import jax
        import jax.numpy as jnp
        env_j = self.run_prefix(j)
        names = self._suffix_free_inputs(j, env_j)
        if not names:
            return None, {}

        def f(vals):
            e = {k: v for k, v in env_j.items() if k != "__len__"}
            e.update(zip(names, vals))
            for i in range(j, self.bi):
                exec_op(e, self.ops[i], i, self.base_key, self.is_test,
                        self.place, self.block)
            return jnp.sum(e[loss_name].astype(jnp.float32))

        grads = jax.grad(f)([env_j[n] for n in names])
        return dict(zip(names, grads)), env_j

    def backward_culprit(self):
        """Param grads are non-finite: binary search the op suffix whose
        backward first emits them. Returns a report (never None — at
        minimum it blames the whole backward section)."""
        loss_name = self.ops[self.bi].attrs["loss_name"]
        lo, hi = 0, self.bi       # S(0)=True (full grads known bad)
        lo_grads, lo_env = self._suffix_grads(0, loss_name)
        while hi - lo > 1:
            mid = (lo + hi) // 2
            grads, env = self._suffix_grads(mid, loss_name)
            if grads is not None and any(_nonfinite(g)
                                         for g in grads.values()):
                lo, lo_grads, lo_env = mid, grads, env
            else:
                hi = mid
        op = self.ops[lo]
        bad = [n for n, g in (lo_grads or {}).items() if _nonfinite(g)]
        grad_stats = [tensor_stats(lo_grads[n], f"d(loss)/d({n})")
                      for n in bad]
        env_in = self.run_prefix(lo)
        return self.report(
            "backward", op, pruned_idx=lo,
            input_stats=_op_stats(op, env_in, "inputs"),
            output_stats=grad_stats,
            nonfinite_vars=[f"{n}@GRAD" for n in bad],
            detail="forward values are finite; the gradient first "
                   "goes non-finite in this op's backward "
                   "(non-finite grads w.r.t. its inputs, finite grads "
                   "w.r.t. its outputs)")

    def full_grads(self):
        """(grads, env_after_forward) exactly as trace.build_step_fn
        computes them: value_and_grad over the param diff set."""
        import jax
        import jax.numpy as jnp
        bop = self.ops[self.bi]
        pnames = bop.attrs["param_names"]
        loss_name = bop.attrs["loss_name"]
        base_env = {k: v for k, v in self.env0.items()}

        def fwd(pvals):
            e = dict(base_env)
            e.update(pvals)
            for i in range(self.bi):
                exec_op(e, self.ops[i], i, self.base_key, self.is_test,
                        self.place, self.block)
            return jnp.sum(e[loss_name].astype(jnp.float32)), e

        pvals = {n: self.env0[n] for n in pnames
                 if n in self.env0}
        (_, env), grads = jax.value_and_grad(fwd, has_aux=True)(pvals)
        return grads, env

    # -------------------------------------------------- update phase
    def update_culprit(self, grads, env):
        """Replay the optimizer tail per-op; first bad output wins."""
        env = dict(env)
        for n, g in grads.items():
            env[grad_var_name(n)] = g.astype(env[n].dtype) \
                if hasattr(g, "astype") else g
        for i in range(self.bi + 1, len(self.ops)):
            op = self.ops[i]
            env_before = dict(env)
            exec_op(env, op, i, self.base_key, self.is_test,
                    self.place, self.block)
            bad = self.bad_outputs(env, i, i + 1)
            if bad:
                return self.report(
                    "update", op, pruned_idx=i,
                    input_stats=_op_stats(op, env_before, "inputs"),
                    output_stats=_op_stats(op, env, "outputs"),
                    nonfinite_vars=bad,
                    detail="forward and gradients are finite; this "
                           "optimizer-tail op produced the first "
                           "non-finite state")
        return None


def localize(program, feed, persist, fetch_names, is_test=False,
             place=None, seed=0, step=0):
    """Find the first op of `program` whose execution goes non-finite
    when re-run against the given pre-step state.

    feed/persist: {name: array} as of BEFORE the failing step (the
    executor snapshots donated persistables when check mode is on).
    Returns a NumericsReport, or None when re-execution stays finite
    (e.g. the failure was not reproducible).
    """
    from .. import telemetry as _tm
    with _tm.span("diagnostics.localize"):
        s = _Session(program, feed, persist, fetch_names, is_test,
                     place, seed, step)
        # phase 0: state that was bad before any op ran
        bad_in = [k for k, v in s.env0.items() if _nonfinite(v)]
        if bad_in:
            return s.report(
                "input", None,
                input_stats=[tensor_stats(s.env0[k], k)
                             for k in bad_in[:16]],
                nonfinite_vars=bad_in,
                detail="feeds/persistable state were non-finite "
                       "before the step executed a single op")
        n_fwd = s.bi if s.bi is not None else len(s.ops)
        rep = s.forward_culprit(n_fwd)
        if rep is not None or s.bi is None:
            return rep
        grads, env = s.full_grads()
        if any(_nonfinite(g) for g in grads.values()):
            return s.backward_culprit()
        return s.update_culprit(grads, env)
