"""Host-side streaming metrics.

Parity: python/paddle/fluid/metrics.py — Accuracy, Precision, Recall,
F1, Auc, CompositeMetric, ChunkEvaluator-lite.
"""
import numpy as np

__all__ = ["MetricBase", "Accuracy", "Precision", "Recall", "F1",
           "Auc", "CompositeMetric", "EditDistance", "ChunkEvaluator",
           "DetectionMAP"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1.0):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no updates yet")
        return self.value / self.weight


class _PRBase(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        p = (preds > 0.5).astype(np.int64) if preds.dtype.kind == "f" else preds
        self.tp += int(np.sum((p == 1) & (labels == 1)))
        self.fp += int(np.sum((p == 1) & (labels == 0)))
        self.fn += int(np.sum((p == 0) & (labels == 1)))


class Precision(_PRBase):
    def eval(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(_PRBase):
    def eval(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class F1(_PRBase):
    def eval(self):
        p = self.tp / (self.tp + self.fp) if (self.tp + self.fp) else 0.0
        r = self.tp / (self.tp + self.fn) if (self.tp + self.fn) else 0.0
        return 2 * p * r / (p + r) if (p + r) else 0.0


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._n = num_thresholds + 1
        self.reset()

    def reset(self):
        self.stat_pos = np.zeros(self._n)
        self.stat_neg = np.zeros(self._n)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:
            preds = preds[:, -1]
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((preds * (self._n - 1)).astype(int), 0, self._n - 1)
        np.add.at(self.stat_pos, idx, labels)
        np.add.at(self.stat_neg, idx, 1 - labels)

    def eval(self):
        pos_c = np.cumsum(self.stat_pos[::-1])
        neg_c = np.cumsum(self.stat_neg[::-1])
        tot_pos = max(pos_c[-1], 1e-9)
        tot_neg = max(neg_c[-1], 1e-9)
        return float(np.trapezoid(pos_c / tot_pos, neg_c / tot_neg))


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0

    def update(self, dists, seq_num=None):
        d = np.asarray(dists).reshape(-1)
        self.total += float(d.sum())
        self.count += len(d)

    def eval(self):
        return self.total / max(self.count, 1)


class ChunkEvaluator(MetricBase):
    """ref metrics.py:ChunkEvaluator — streaming chunk-level P/R/F1 from
    the chunk_eval op's (num_infer, num_label, num_correct) counters."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class DetectionMAP(MetricBase):
    """ref metrics.py:DetectionMAP — streaming mean over per-batch mAP
    values produced by layers.detection_map."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0

    def update(self, value, weight=1):
        self.total += float(np.asarray(value).sum()) * weight
        self.count += weight

    def eval(self):
        return self.total / max(self.count, 1)
