"""API deprecation annotations (ref python/paddle/fluid/annotations.py).

One decorator, `deprecated(since, instead)`, printed once per call site
in the reference; here it warns once per function (warnings module, so
filters/`-W error` behave normally) and still forwards the call.
"""
import functools
import warnings

__all__ = ["deprecated"]


def deprecated(since, instead, extra_message=""):
    def decorator(func):
        msg = (f"API {func.__name__} is deprecated since {since}. "
               f"Please use {instead} instead.")
        if extra_message:
            msg += "\n" + extra_message

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        wrapper.__doc__ = (wrapper.__doc__ or "") + "\n    " + msg
        return wrapper

    return decorator
