"""Program surgery helpers (ref transpiler/details/program_utils.py)
over this framework's Block/Operator IR."""


def delete_ops(block, ops):
    """Remove the given Operator objects from the block (identity
    match), ignoring ones already gone — ref delete_ops, without the
    reference's print-and-continue on errors."""
    keep = [op for op in block.ops if all(op is not o for o in ops)]
    block.ops = keep
    block.program._bump_version()


def find_op_by_input_arg(block, arg_name):
    """Index of the first op consuming arg_name, else -1."""
    for index, op in enumerate(block.ops):
        if arg_name in op.input_names():
            return index
    return -1


def find_op_by_output_arg(block, arg_name, reverse=False):
    """Index of the first (or last, reverse=True) op producing
    arg_name, else -1."""
    ops = list(enumerate(block.ops))
    if reverse:
        ops = reversed(ops)
    for index, op in ops:
        if arg_name in op.output_names():
            return index
    return -1
