"""Union-find (ref transpiler/details/ufind.py — used by the reference
to group variables that must share a pserver placement; kept for API
parity and generally useful for graph partitioning)."""


class UnionFind:
    def __init__(self, elements=None):
        self._parents = {}
        for e in elements or []:
            self._parents[e] = e

    def _root(self, x):
        if x not in self._parents:
            return None
        while self._parents[x] != x:
            self._parents[x] = self._parents[self._parents[x]]
            x = self._parents[x]
        return x

    def find(self, x):
        """Root of x's set (the reference returns -1 for unknowns)."""
        r = self._root(x)
        return -1 if r is None else r

    def union(self, x, y):
        for e in (x, y):
            if e not in self._parents:
                self._parents[e] = e
        rx, ry = self._root(x), self._root(y)
        if rx != ry:
            self._parents[rx] = ry

    def is_connected(self, x, y):
        rx = self._root(x)
        return rx is not None and rx == self._root(y)
