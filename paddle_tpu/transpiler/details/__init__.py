"""Transpiler helper utilities (ref
python/paddle/fluid/transpiler/details/: program_utils.py, ufind.py,
checkport.py). Internal to the reference's distribute transpiler but
imported by downstream code, so kept name-for-name; implementations
are original over this framework's Program IR.
"""
from .checkport import wait_server_ready
from .program_utils import (delete_ops, find_op_by_input_arg,
                            find_op_by_output_arg)
from .ufind import UnionFind

__all__ = ["delete_ops", "find_op_by_input_arg", "find_op_by_output_arg",
           "UnionFind", "wait_server_ready"]
