"""wait_server_ready (ref transpiler/details/checkport.py).

The reference polls pserver endpoints before trainers start. There are
no pservers here, but the SAME need exists for the multi-host
coordinator (`fleet.init` → jax.distributed): trainers on other hosts
can poll the coordinator endpoint with this exact call.
"""
import socket
import sys
import time
from contextlib import closing


def wait_server_ready(endpoints, timeout_s=None, poll_interval=3.0):
    """Block until every "ip:port" endpoint accepts TCP connections.
    timeout_s (extension): give up and raise after this many seconds —
    the reference spins forever, which in a gang-scheduled TPU job
    turns a dead peer into a silent hang."""
    deadline = None if timeout_s is None else time.time() + timeout_s
    while True:
        not_ready = []
        for ep in endpoints:
            ip, port = ep.rsplit(":", 1)
            with closing(socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)) as sock:
                sock.settimeout(2)
                if sock.connect_ex((ip, int(port))) != 0:
                    not_ready.append(ep)
        if not not_ready:
            return
        if deadline is not None and time.time() > deadline:
            raise TimeoutError(
                f"servers not ready after {timeout_s}s: {not_ready}")
        sys.stderr.write(f"pending server endpoints: {not_ready}\n")
        sys.stderr.flush()
        time.sleep(poll_interval)
