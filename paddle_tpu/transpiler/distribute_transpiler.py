"""Alias at the reference's import path.

Parity: python/paddle/fluid/transpiler/distribute_transpiler.py —
implementation in parallel/transpiler.py (SPMD sharding over the mesh
replaces the pserver/NCCL program rewrite).
"""
from ..parallel.transpiler import (DistributeTranspiler,  # noqa: F401
                                   DistributeTranspilerConfig)
