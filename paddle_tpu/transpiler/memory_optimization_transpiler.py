"""Memory optimization.

Parity: python/paddle/fluid/transpiler/memory_optimization_transpiler.py.
The reference reuses out-of-liveness buffers inside the ProgramDesc; under
XLA, buffer liveness/reuse is the compiler's job already, so the lever
that actually reduces peak HBM here is REMATERIALIZATION: memory_optimize
marks the program so the traced forward runs under jax.checkpoint and
activations are recomputed in the backward pass (FLOPs for memory — the
same trade the reference's transpiler makes by freeing+recomputing).
"""

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=False):
    """Enable forward rematerialization for `input_program`. Returns the
    estimated activation bytes saved (vars between forward and backward)."""
    input_program._remat = True
    saved = 0
    from ..core.dtypes import dtype_size
    for v in input_program.list_vars():
        if v.persistable or v.is_data:
            continue
        if skip_opt_set and v.name in skip_opt_set:
            continue
        n = 1
        for s in v.shape:
            n *= max(int(s), 1)
        saved += n * dtype_size(v.dtype)
    if print_log:
        print(f"memory_optimize: rematerialization enabled, "
              f"~{saved / 1e6:.1f} MB of activations freed from the "
              f"forward residency set")
    return saved


def release_memory(input_program, skip_opt_set=None):
    """ref transpiler.release_memory — inserts delete ops in the
    reference; XLA/PJRT frees dead buffers automatically, so this only
    keeps API parity (no-op)."""
    return input_program
