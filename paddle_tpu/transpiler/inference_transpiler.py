"""Inference-time graph optimization.

Parity: python/paddle/fluid/transpiler/inference_transpiler.py — fold
batch_norm into the preceding conv2d (the reference also relies on MKLDNN
fusions; under XLA elementwise chains fuse automatically, so the one
rewrite that still pays is the conv+bn WEIGHT fold, which removes the bn
op and its 4 parameter tensors from the graph entirely):

    w' = w * scale / sqrt(var + eps)
    b' = (b - mean) * scale / sqrt(var + eps) + bias_bn
"""
import numpy as np

__all__ = ["InferenceTranspiler"]


class InferenceTranspiler:
    def transpile(self, program, place=None, scope=None):
        """Rewrite `program` in place using parameter values from `scope`
        (defaults to the global scope). Run AFTER the startup program /
        param load, on an inference (is_test) program."""
        from ..core.scope import global_scope
        scope = scope or global_scope()
        self._fuse_batch_norm(program, scope)

    # ------------------------------------------------------------------
    def _fuse_batch_norm(self, program, scope):
        block = program.global_block()
        ops = block.ops
        i = 0
        while i < len(ops) - 1:
            op = ops[i]
            # conv only (like the reference): the mul kernel has no Bias
            # slot to fold the shift into
            if op.type not in ("conv2d", "depthwise_conv2d"):
                i += 1
                continue
            out_name = op.outputs.get("Out", op.outputs.get("Output", [None]))[0]
            nxt = ops[i + 1]
            if nxt.type != "batch_norm" or \
                    nxt.inputs.get("X", [None])[0] != out_name:
                i += 1
                continue
            if not nxt.attrs.get("is_test", False) and \
                    not getattr(program, "_is_test", False):
                # folding uses the FROZEN moving stats — training bn stays
                i += 1
                continue
            w_name = op.inputs["Filter"][0]
            scale = np.asarray(scope.get(nxt.inputs["Scale"][0]))
            bias = np.asarray(scope.get(nxt.inputs["Bias"][0]))
            mean = np.asarray(scope.get(nxt.inputs["Mean"][0]))
            var = np.asarray(scope.get(nxt.inputs["Variance"][0]))
            eps = nxt.attrs.get("epsilon", 1e-5)
            w = np.asarray(scope.get(w_name))
            alpha = scale / np.sqrt(var + eps)
            if w.ndim == 4:          # OIHW conv filter: scale output chans
                w2 = w * alpha[:, None, None, None]
            else:                    # [in, out] matmul weight
                w2 = w * alpha[None, :]
            import jax.numpy as jnp
            scope.set(w_name, jnp.asarray(w2, dtype=str(w.dtype)))
            # fold the shift into a conv bias (create one if absent)
            b_names = op.inputs.get("Bias")
            shift = bias - mean * alpha
            if b_names:
                b_old = np.asarray(scope.get(b_names[0]))
                scope.set(b_names[0],
                          jnp.asarray(b_old * alpha + shift,
                                      dtype=str(b_old.dtype)))
            else:
                b_name = w_name + ".bn_fold_bias"
                block.create_var(name=b_name, shape=shift.shape,
                                 dtype="float32", persistable=True)
                scope.set(b_name, jnp.asarray(shift, np.float32))
                op.inputs["Bias"] = [b_name]
            # the conv now writes straight into the bn's output var, so
            # downstream consumers AND fetches of the bn var keep working
            bn_out = nxt.outputs["Y"][0]
            out_slot = "Output" if "Output" in op.outputs else "Out"
            op.outputs[out_slot] = [bn_out]
            del ops[i + 1]
            for later in ops[i + 1:]:
                for slot, names in later.inputs.items():
                    later.inputs[slot] = [bn_out if n == out_name else n
                                          for n in names]
            program._bump_version()
            i += 1
