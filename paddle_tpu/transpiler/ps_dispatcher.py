"""Parameter-server dispatchers.

Parity: python/paddle/fluid/transpiler/ps_dispatcher.py — map variables
onto pserver endpoints. On TPU the analog is assigning optimizer-state
shards to mesh coordinates (ZeRO-style); these classes keep the
reference API for distribute-transpiler callers.
"""

__all__ = ["PSDispatcher", "HashName", "RoundRobin"]


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError("Interface has not been implemented.")


class HashName(PSDispatcher):
    """ref ps_dispatcher.py:HashName — endpoint = hash(var name) % n."""

    def _hash_block(self, block_str, total):
        return hash(block_str) % total

    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            server_id = self._hash_block(var.name(), len(self._eps)) \
                if callable(getattr(var, "name", None)) \
                else self._hash_block(var.name, len(self._eps))
            eplist.append(self._eps[server_id])
        return eplist


class RoundRobin(PSDispatcher):
    """ref ps_dispatcher.py:RoundRobin — cycle endpoints in order."""

    def dispatch(self, varlist):
        eplist = []
        for _ in varlist:
            eplist.append(self._eps[self._step])
            self._step = (self._step + 1) % len(self._eps)
        return eplist
