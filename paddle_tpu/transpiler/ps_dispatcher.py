"""Variable → owner assignment policies.

API parity with the reference's pserver dispatchers
(python/paddle/fluid/transpiler/ps_dispatcher.py), re-purposed for the
TPU design: there are no pserver endpoints, so the "endpoints" these
policies cycle/hash over are the ZeRO shard owners — the dp-axis mesh
members that hold a variable's optimizer-state shard
(parallel/sharding.py:zero_stage is the layout these feed).
"""
import itertools
import zlib

__all__ = ["PSDispatcher", "HashName", "RoundRobin"]


class PSDispatcher:
    """Base policy: assign each var (or var block) an owner from `eplist`
    — a list of endpoint strings for API compat, or mesh coordinates."""

    def __init__(self, eplist):
        self._eplist = list(eplist)

    @property
    def eps(self):
        return self._eplist

    def reset(self):
        pass

    def dispatch(self, varlist):
        raise NotImplementedError

    def owner(self, var):
        """Single-var convenience: owner of `var` under this policy."""
        return self.dispatch([var])[0]


def _var_name(v):
    name = getattr(v, "name", v)
    return name() if callable(name) else name


class HashName(PSDispatcher):
    """Stable content-hash assignment: the same var name always lands on
    the same owner regardless of dispatch order (crc32, not Python's
    salted hash, so placements are reproducible across processes)."""

    def dispatch(self, varlist):
        n = len(self._eplist)
        return [self._eplist[zlib.crc32(str(_var_name(v)).encode()) % n]
                for v in varlist]


class RoundRobin(PSDispatcher):
    """Cyclic assignment in dispatch order (balances shard count, not
    bytes — use HashName for order-independent placement)."""

    def __init__(self, eplist):
        super().__init__(eplist)
        self._cycle = itertools.cycle(range(len(self._eplist)))

    def reset(self):
        self._cycle = itertools.cycle(range(len(self._eplist)))

    def dispatch(self, varlist):
        return [self._eplist[next(self._cycle)] for _ in varlist]
