"""Program transpilers.

Parity: python/paddle/fluid/transpiler/__init__.py — DistributeTranspiler
(SPMD sharding rules over the mesh, parallel/transpiler.py),
InferenceTranspiler (conv+bn folding), memory_optimize (rematerialization)
and the pserver dispatchers.
"""
from ..parallel.transpiler import (DistributeTranspiler,
                                   DistributeTranspilerConfig)
from .inference_transpiler import InferenceTranspiler
from .memory_optimization_transpiler import memory_optimize, release_memory
from .ps_dispatcher import HashName, RoundRobin

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "InferenceTranspiler", "memory_optimize", "release_memory",
           "HashName", "RoundRobin"]
