"""DataFeed descriptor.

Parity: python/paddle/fluid/data_feed_desc.py — parse the reference's
protobuf-text DataFeedDesc format (framework/data_feed.proto):

    name: "MultiSlotDataFeed"
    batch_size: 2
    multi_slot_desc {
        slots { name: "words" type: "uint64" is_dense: false is_used: true }
        slots { name: "label" type: "uint64" is_dense: false is_used: true }
    }

A small hand parser replaces the protobuf dependency (same accepted
surface: name/batch_size/multi_slot_desc.slots fields).
"""
import re

__all__ = ["DataFeedDesc"]


class _Slot:
    def __init__(self):
        self.name = None
        self.type = "float32"
        self.is_dense = False
        self.is_used = True
        self.shape = []


class DataFeedDesc:
    def __init__(self, proto_file):
        with open(proto_file) as f:
            text = f.read()
        self.proto_desc_name = self._scalar(text, "name", "MultiSlotDataFeed")
        self.batch_size = int(self._scalar(text, "batch_size", 1))
        self.slots = []
        self._slot_index = {}
        for m in re.finditer(r"slots\s*\{(.*?)\}", text, re.S):
            body = m.group(1)
            s = _Slot()
            s.name = self._scalar(body, "name", None)
            s.type = self._scalar(body, "type", "float32").strip('"')
            s.is_dense = self._scalar(body, "is_dense", "false") == "true"
            s.is_used = self._scalar(body, "is_used", "true") == "true"
            s.shape = [int(x) for x in re.findall(r"shape:\s*(-?\d+)", body)]
            self.slots.append(s)
            self._slot_index[s.name] = len(self.slots) - 1

    @staticmethod
    def _scalar(text, key, default):
        m = re.search(rf"\b{key}\s*:\s*(\"[^\"]*\"|\S+)", text)
        if not m:
            return default
        return m.group(1).strip('"')

    # -- reference API -----------------------------------------------------
    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_dense_slots(self, dense_slots_name):
        for name in dense_slots_name:
            self.slots[self._slot_index[name]].is_dense = True

    def set_use_slots(self, use_slots_name):
        for s in self.slots:
            s.is_used = False
        for name in use_slots_name:
            self.slots[self._slot_index[name]].is_used = True

    def desc(self):
        lines = [f'name: "{self.proto_desc_name}"',
                 f"batch_size: {self.batch_size}", "multi_slot_desc {"]
        for s in self.slots:
            lines.append(
                f'  slots {{ name: "{s.name}" type: "{s.type}" '
                f"is_dense: {str(s.is_dense).lower()} "
                f"is_used: {str(s.is_used).lower()} }}")
        lines.append("}")
        return "\n".join(lines)
