"""Parameter initializers.

Parity: python/paddle/fluid/initializer.py. Each initializer appends an
init op to the STARTUP program (fill_constant / uniform_random /
gaussian_random / ...), exactly like the reference; the Executor runs the
startup program once to materialize params in the Scope.
"""
import numpy as np

__all__ = [
    "Initializer", "Constant", "Uniform", "Normal", "TruncatedNormal",
    "Xavier", "MSRA", "Bilinear", "NumpyArrayInitializer",
    "ConstantInitializer", "UniformInitializer", "NormalInitializer",
    "XavierInitializer", "MSRAInitializer", "init_on_cpu",
    "force_init_on_cpu",
]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op("fill_constant", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "value": float(self.value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op("uniform_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "min": self.low, "max": self.high, "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": self.loc, "std": self.scale, "seed": self.seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op("truncated_gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": self.loc, "std": self.scale, "seed": self.seed})


def _fans(var):
    shape = var.shape
    if len(shape) == 2:
        return shape[0], shape[1]
    if len(shape) >= 2:
        rf = int(np.prod(shape[2:]))
        return shape[1] * rf, shape[0] * rf
    return int(np.prod(shape)), int(np.prod(shape))


class XavierInitializer(Initializer):
    """Glorot init (ref initializer.py:XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fans(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = float(np.sqrt(6.0 / (fi + fo)))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / (fi + fo)))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """Kaiming/He init (ref initializer.py:MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fans(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = float(np.sqrt(6.0 / fi))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = float(np.sqrt(2.0 / fi))
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """For conv-transpose upsampling weights (ref initializer.py)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects 4-D weights")
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        w = np.zeros(shape, dtype="float32")
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            idx = np.unravel_index(i, shape)
            w[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        NumpyArrayInitializer(w)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op("assign_value", outputs={"Out": [var.name]},
                        attrs={"shape": list(self.value.shape),
                               "dtype": var.dtype,
                               "values": self.value.reshape(-1).tolist()})


# Fluid-style aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


import contextlib as _contextlib

_force_init_on_cpu_ = False


def force_init_on_cpu():
    """ref initializer.py:force_init_on_cpu — whether init ops are pinned
    to host. On TPU initialization compiles into the startup module and
    runs where XLA places it; the flag is kept for API parity."""
    return _force_init_on_cpu_


@_contextlib.contextmanager
def init_on_cpu():
    """Context manager forcing init on CPU (ref init_on_cpu)."""
    global _force_init_on_cpu_
    prev = _force_init_on_cpu_
    _force_init_on_cpu_ = True
    try:
        yield
    finally:
        _force_init_on_cpu_ = prev
