"""Gradient clipping.

Parity: python/paddle/fluid/clip.py — ByValue / ByNorm per-grad ops,
ByGlobalNorm as ONE op over all grads (the joint norm reduction then
compiles into the same XLA module as the update).
"""
__all__ = ["GradientClipByValue", "GradientClipByNorm",
           "GradientClipByGlobalNorm", "set_gradient_clip",
           "append_gradient_clip_ops", "ErrorClipByValue"]

_global_clip = None


class BaseGradientClipAttr:
    def _append_clip_op(self, block, grad):
        return grad


class ErrorClipByValue:
    """Accepted for API parity; error clipping is a no-op in whole-program
    autodiff (activations' grads aren't materialized individually)."""

    def __init__(self, max, min=None):
        self.max, self.min = max, min


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _append_clip_op(self, block, grad):
        block.append_op("clip", {"X": [grad]}, {"Out": [grad]},
                        {"min": self.min, "max": self.max})
        return grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _append_clip_op(self, block, grad):
        block.append_op("clip_by_norm", {"X": [grad]}, {"Out": [grad]},
                        {"max_norm": self.clip_norm})
        return grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm
        self.group_name = group_name


def set_gradient_clip(clip, param_list=None, program=None):
    global _global_clip
    _global_clip = clip
    if param_list:
        for p in param_list:
            if hasattr(p, "gradient_clip_attr"):
                p.gradient_clip_attr = clip


def append_gradient_clip_ops(params_grads):
    """ref clip.py:append_gradient_clip_ops — runs between backward and
    optimizer update."""
    if not params_grads:
        return params_grads
    block = params_grads[0][1].block
    global_items = []
    out = []
    for p, g in params_grads:
        clip = getattr(p, "gradient_clip_attr", None) or _global_clip
        if clip is None:
            out.append((p, g))
        elif isinstance(clip, GradientClipByGlobalNorm):
            global_items.append((p, g, clip))
            out.append((p, g))
        else:
            clip._append_clip_op(block, g)
            out.append((p, g))
    if global_items:
        clip_norm = global_items[0][2].clip_norm
        grads = [g for _, g, _ in global_items]
        block.append_op("global_norm_clip",
                        {"X": grads}, {"Out": grads},
                        {"max_global_norm": clip_norm})
    return out
