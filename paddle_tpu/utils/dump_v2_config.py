"""Topology dumping.

Parity: python/paddle/utils/dump_v2_config.py — the reference walks a
legacy-v2 layer graph back to its data layers and serializes the
TrainerConfig proto. Here topology is Variable(s) of a Program (the
rebuild's only graph form): the program is pruned to the ops feeding
the given outputs and its desc is written to `save_path` (JSON text, or
pickled bytes with binary=True — the C-API-serialized analog).
"""
import collections.abc
import json
import pickle

__all__ = ["dump_v2_config"]


def dump_v2_config(topology, save_path, binary=False):
    from ..core.framework import Variable

    if isinstance(topology, Variable):
        topology = [topology]
    elif isinstance(topology, collections.abc.Sequence):
        if not topology:
            raise ValueError("topology must contain at least one "
                             "output Variable")
        for out in topology:
            if not isinstance(out, Variable):
                raise TypeError(
                    "each element of topology must be a Variable, got "
                    f"{type(out).__name__}")
    else:
        raise TypeError(
            "topology must be a Variable or a sequence of Variables")
    program = topology[0].block.program
    from ..io import _prune_for_inference
    pruned = _prune_for_inference(program, [],
                                  [v.name for v in topology])
    desc = pruned.to_desc()
    if binary:
        with open(save_path, "wb") as f:
            pickle.dump(desc, f, protocol=4)
    else:
        with open(save_path, "w") as f:
            json.dump(desc, f, indent=1, default=str)
    return save_path
