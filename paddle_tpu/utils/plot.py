"""Training-curve plotting helper.

Parity: python/paddle/utils/plot.py:Ploter — the book chapters append
(title, step, cost) points and draw in notebooks. Headless-safe: data
is always recorded; drawing happens only when matplotlib imports (its
own backend auto-selection handles display-less hosts). DISABLE_PLOT=
True is captured at construction, matching the reference.
"""
import os

__all__ = ["Ploter", "PlotData"]


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {t: PlotData() for t in args}
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT")

    def __plot_is_disabled__(self):
        return self.__disable_plot__ == "True"

    def _pyplot(self):
        try:
            import matplotlib.pyplot as plt
            return plt
        except Exception:
            return None  # record-only mode

    def append(self, title, step, value):
        assert title in self.__plot_data__, (
            f"{title} not in the Ploter titles {self.__args__}")
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        if self.__plot_is_disabled__():
            return
        plt = self._pyplot()
        if plt is None:
            return
        try:
            titles = []
            for title in self.__args__:
                data = self.__plot_data__[title]
                if len(data.step) > 0:
                    plt.plot(data.step, data.value)
                    titles.append(title)
            if not titles:
                return  # nothing recorded yet: no empty figure/warning
            plt.legend(titles, loc="upper left")
            if path:
                plt.savefig(path)
            plt.clf()
        except Exception:
            return  # broken DISPLAY/backend: record-only degrade

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
