"""paddle.utils compat (the book-demo helpers).

Parity: python/paddle/utils — the pieces with a live export surface:
plot.Ploter (book demos), dump_v2_config (topology dumping, rebuilt
over Program desc), image_multiproc (process-pool image transforms).
The remaining v1-era converters (torch2paddle, merge_model, ...)
predate fluid and are out of scope (SURVEY §2 covers the fluid
framework surface).
"""
from . import plot  # noqa: F401
from . import dump_v2_config  # noqa: F401
from . import image_multiproc  # noqa: F401
from .dump_v2_config import dump_v2_config as _dump  # noqa: F401
