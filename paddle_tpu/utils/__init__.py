"""paddle.utils compat (the book-demo helpers).

Parity: python/paddle/utils — only the pieces the fluid book/demos use
(plot.Ploter); the v1-era converters (dump_config, torch2paddle, ...)
predate fluid and are out of scope (SURVEY §2 covers the fluid
framework surface).
"""
from . import plot  # noqa: F401
