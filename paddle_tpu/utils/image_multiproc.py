"""Multi-process image preprocessing.

Parity: python/paddle/utils/image_multiproc.py — transform a batch of
images in a process pool. The reference offers cv2- and PIL-backed
transformers; here ONE numpy implementation (paddle_tpu.dataset.image,
PIL for decoding) serves both names, with the same knob surface
(resize/crop/transpose/channel_swap/mean/flip-in-train).
"""
import numpy as np

from ..dataset import image as _img

__all__ = ["CvTransformer", "PILTransformer",
           "MultiProcessImageTransformer"]


class _Transformer:
    def __init__(self, resize_size=None, crop_size=None,
                 transpose=(2, 0, 1), channel_swap=None, mean=None,
                 is_train=True, is_color=True):
        self.resize_size = resize_size
        self.crop_size = crop_size
        self.transpose = transpose
        self.channel_swap = channel_swap
        self.mean = mean
        self.is_train = is_train
        self.is_color = is_color

    def transform(self, im):
        if self.resize_size is not None:
            im = _img.resize_short(im, self.resize_size)
        if self.crop_size is not None:
            if self.is_train:
                im = _img.random_crop(im, self.crop_size,
                                      is_color=self.is_color)
                if np.random.randint(2):
                    im = _img.left_right_flip(im, self.is_color)
            else:
                im = _img.center_crop(im, self.crop_size,
                                      is_color=self.is_color)
        if im.ndim == 3:
            if self.channel_swap is not None:
                im = im[:, :, list(self.channel_swap)]
            if self.transpose is not None:
                im = im.transpose(self.transpose)
        im = im.astype("float32")
        if self.mean is not None:
            mean = np.asarray(self.mean, "float32")
            im -= mean if mean.ndim != 1 else mean[:, None, None]
        return im

    def transform_from_string(self, data):
        return self.transform(_img.load_image_bytes(data, self.is_color))

    def transform_from_file(self, file_name):
        return self.transform(_img.load_image(file_name, self.is_color))


class CvTransformer(_Transformer):
    """ref image_multiproc.py:36 (cv2-backed there; see module doc)."""


class PILTransformer(_Transformer):
    """ref image_multiproc.py:118."""


def _job(args):
    is_img_string, transformer, im, label = args
    if is_img_string:
        return transformer.transform_from_string(im), label
    return transformer.transform_from_file(im), label


class MultiProcessImageTransformer:
    """Transform (image, label) pairs in a process pool; `run(data,
    labels)` yields results as they complete (ref
    image_multiproc.py:199)."""

    def __init__(self, procnum=10, resize_size=None, crop_size=None,
                 transpose=(2, 0, 1), channel_swap=None, mean=None,
                 is_train=True, is_color=True, is_img_string=True):
        self.procnum = procnum
        self.is_img_string = is_img_string
        self.transformer = CvTransformer(
            resize_size=resize_size, crop_size=crop_size,
            transpose=transpose, channel_swap=channel_swap, mean=mean,
            is_train=is_train, is_color=is_color)
        self._pool = None

    @property
    def pool(self):
        import multiprocessing
        if self._pool is None:
            self._pool = multiprocessing.Pool(processes=self.procnum)
        return self._pool

    def run(self, data, label):
        args = [(self.is_img_string, self.transformer, im, lab)
                for im, lab in zip(data, label)]
        return self.pool.imap(_job, args)

    def close(self):
        if self._pool is not None:
            self._pool.close()
            self._pool.join()
            self._pool = None
