#!/usr/bin/env python
"""tpustat — run a benchmark model N steps with telemetry on and print
the runtime metrics (the dynamic counterpart of tools/proglint.py).

Builds a model from benchmark/fluid/models/ exactly like
fluid_benchmark.py, runs the startup program, then runs N training
steps with `paddle_tpu.telemetry` enabled and metrics scoped to the
steady-state loop (the startup compile is excluded). Prints a metrics
table (or one JSON line with --json) and writes the merged Chrome
trace-event timeline, loadable in chrome://tracing / Perfetto.

--json validates the snapshot (counter arithmetic, histogram
consistency, trace well-formedness) and exits non-zero when the
metrics are malformed, so it doubles as a CI gate.

Examples:
  python tools/tpustat.py --model mnist --steps 20 --json
  python tools/tpustat.py --model resnet --steps 10 --prom
  python tools/tpustat.py --model mnist --platform env   # real backend
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "benchmark", "fluid"))
sys.path.insert(0, os.path.join(_REPO, "tools"))

from proglint import ALL_MODELS, model_args  # noqa: E402


def build_model(name, args=None):
    """(main_program, startup_program, loss, feed_fn) — the proglint
    builder plus the model's synthetic feed generator, which tpustat
    needs to actually run the steps."""
    import paddle_tpu as fluid
    args = args or model_args()
    model_mod = __import__(f"models.{name}", fromlist=["get_model"])
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        with fluid.unique_name.guard():
            loss, feed_fn = model_mod.get_model(args)
            opt = fluid.optimizer.Adam(args.learning_rate) \
                if name == "machine_translation" \
                else fluid.optimizer.Momentum(args.learning_rate, 0.9)
            opt.minimize(loss)
    return main_p, startup_p, loss, feed_fn


def validate_metrics(snap, steps):
    """Structural checks over a telemetry snapshot from a `steps`-long
    cached run. Returns a list of problem strings (empty = healthy)."""
    problems = []

    def need(name):
        if name not in snap:
            problems.append(f"missing metric {name!r}")
            return None
        return snap[name]

    compiles = need("executor.compile_count")
    hits = need("executor.cache_hit_count") \
        if "executor.cache_hit_count" in snap else 0
    n_steps = need("executor.steps")
    for name, v in snap.items():
        if isinstance(v, dict):       # histogram
            bucket_total = sum(v.get("buckets", {}).values())
            if bucket_total != v.get("count"):
                problems.append(
                    f"histogram {name!r}: bucket total {bucket_total} "
                    f"!= count {v.get('count')}")
            if v.get("count", 0) < 0 or v.get("sum", 0) < 0:
                problems.append(f"histogram {name!r}: negative count/sum")
        elif isinstance(v, bool) or not isinstance(v, (int, float)):
            problems.append(f"metric {name!r}: non-numeric value {v!r}")
    if isinstance(compiles, int) and isinstance(n_steps, int):
        if n_steps != steps:
            problems.append(
                f"executor.steps {n_steps} != requested steps {steps}")
        if compiles + hits != steps:
            problems.append(
                f"compile_count {compiles} + cache_hit_count {hits} "
                f"!= steps {steps}")
        if compiles < 1:
            problems.append("no compile recorded")
    h = snap.get("executor.step_seconds")
    if isinstance(h, dict) and h.get("count") != steps:
        problems.append(
            f"executor.step_seconds count {h.get('count')} != {steps}")
    return problems


def _fmt_value(v):
    if isinstance(v, dict):
        m = f" mean={v['mean']:.4g}s max={v['max']:.4g}s" \
            if v.get("count") else ""
        return f"hist count={v['count']}{m}"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="runtime telemetry over a benchmark model")
    p.add_argument("--model", default="mnist", choices=ALL_MODELS)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--platform", default="cpu",
                   help="JAX_PLATFORMS to force before backend init "
                        "('env' keeps the environment's value; default "
                        "cpu so the CLI never hangs on a down relay)")
    p.add_argument("--trace", metavar="PATH", default=None,
                   help="Chrome trace output "
                        "(default /tmp/tpustat_<model>.trace.json)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one machine-readable JSON line; exit non-zero "
                        "on malformed metrics")
    p.add_argument("--prom", action="store_true",
                   help="also print the Prometheus text exposition")
    p.add_argument("--profile-device", action="store_true",
                   help="run a short device trace and merge per-op "
                        "device times onto the timeline (needs a "
                        "backend whose xplane layout we can decode)")
    args = p.parse_args(argv)

    if args.platform != "env":
        os.environ["JAX_PLATFORMS"] = args.platform

    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import telemetry

    telemetry.enable()
    main_p, startup_p, loss, feed_fn = build_model(
        args.model, model_args(batch_size=args.batch_size))
    exe = fluid.Executor()
    exe.run(startup_p, feed={}, fetch_list=[])
    # scope the metrics to the steady-state loop: the startup compile
    # is one-off noise next to `steps` worth of hit/miss accounting
    telemetry.reset()

    rng = np.random.RandomState(0)
    losses = []
    for _ in range(args.steps):
        feed = feed_fn(args.batch_size, rng)
        out = exe.run(main_p, feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(out[0]).ravel()[0]))

    device_profile = None
    if args.profile_device:
        from paddle_tpu import profiler
        feed = feed_fn(args.batch_size, rng)
        try:
            per_step, ops = profiler.profile_step_fn(
                lambda: exe.run(main_p, feed=feed, fetch_list=[loss]),
                steps=3)
            device_profile = {"device_step_seconds": per_step,
                              "top_ops": dict(sorted(
                                  ops.items(),
                                  key=lambda kv: -kv[1])[:10])}
        except Exception as e:
            device_profile = {"error": f"{type(e).__name__}: {e}"}

    snap = telemetry.snapshot()
    problems = validate_metrics(snap, args.steps)

    trace_path = args.trace or f"/tmp/tpustat_{args.model}.trace.json"
    telemetry.write_chrome_trace(trace_path)
    try:
        with open(trace_path) as f:
            trace = json.loads(f.read())
        span_events = sum(1 for e in trace.get("traceEvents", [])
                          if e.get("ph") == "X")
        for e in trace.get("traceEvents", []):
            if e.get("ph") == "X" and ("ts" not in e or "dur" not in e):
                problems.append("trace X event missing ts/dur")
                break
        if span_events < args.steps:
            problems.append(
                f"trace has {span_events} span events < steps "
                f"{args.steps}")
    except (OSError, ValueError) as e:
        span_events = 0
        problems.append(f"trace does not round-trip: {e}")

    import jax
    # signature explosion at a glance: distinct compiled signatures
    # across the executor and inference engines (each gauge is set at
    # compile time — see executor.run / InferenceEngine._get_fn)
    signatures = int(max(snap.get("executor.signature_count", 0),
                         snap.get("inference.signature_count", 0)))
    from paddle_tpu import diagnostics
    diag = diagnostics.status()
    result = {
        "model": args.model,
        "steps": args.steps,
        "batch_size": args.batch_size,
        "platform": jax.devices()[0].platform,
        "diagnostics": diag,
        "signatures": signatures,
        "final_loss": losses[-1] if losses else None,
        "metrics": snap,
        "trace": {"path": trace_path, "span_events": span_events},
        "problems": problems,
        "ok": not problems,
    }
    if device_profile is not None:
        result["device_profile"] = device_profile

    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        print(f"tpustat: {args.model} x {args.steps} steps "
              f"(batch {args.batch_size}) on "
              f"{result['platform']}, {signatures} compiled "
              f"signature{'s' if signatures != 1 else ''}, "
              f"nan_check={'on' if diag['nan_check'] else 'off'} "
              f"flight_recorder="
              f"{'on' if diag['flight_recorder'] else 'off'}")
        width = max((len(k) for k in snap), default=10)
        for name in sorted(snap):
            print(f"  {name:<{width}}  {_fmt_value(snap[name])}")
        print(f"trace: {trace_path} ({span_events} span events)")
        if device_profile:
            print(f"device profile: {device_profile}")
        for prob in problems:
            print(f"MALFORMED: {prob}", file=sys.stderr)
    if args.prom:
        print(telemetry.prometheus_text(), end="")
    return 2 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
