#!/usr/bin/env python
"""tpustat — run a benchmark model N steps with telemetry on and print
the runtime metrics (the dynamic counterpart of tools/proglint.py).

Builds a model from benchmark/fluid/models/ exactly like
fluid_benchmark.py, runs the startup program, then runs N training
steps with `paddle_tpu.telemetry` enabled and metrics scoped to the
steady-state loop (the startup compile is excluded). Prints a metrics
table (or one JSON line with --json) and writes the merged Chrome
trace-event timeline, loadable in chrome://tracing / Perfetto.

--json validates the snapshot (counter arithmetic, histogram
consistency, trace well-formedness) and exits non-zero when the
metrics are malformed, so it doubles as a CI gate.

Fleet mode (--fleet): the multi-rank view. Reads a rank-snapshot spool
(telemetry.fleet — every multihost worker flushes rank*.snap.json
there), merges it coordinator-side, and prints per-rank step time,
collective volume, pipeline bubble %, and the straggler verdict from
one command; --trace writes the STITCHED multi-rank Chrome trace (one
pid per rank, clocks aligned on the shared barrier marker).
--fleet --selftest spawns two local single-process workers, merges
their spool, and validates the whole path — the CI gate
tests/test_fleet.py runs.

SLO mode (--slo, "tpuscope"): evaluate declarative perf rules
(telemetry.slo) against the run's snapshot — step_ms.p99 < X,
perf.mfu > Y, serving.queue_depth < Z — plus a MAD-based regression
gate of the newest BENCH_history.jsonl record per metric against its
rolling median (same robust statistics as the fleet straggler
detector). --rules takes a file (one rule per line, # comments) or an
inline ';'-separated list; --history points at an alternate spine.
--slo --selftest validates the whole layer in-process (rule parsing,
live MFU/goodput gauges on a tiny model, an injected step-time
regression that MUST be flagged) — the tier-1 CI gate.

Watch mode (--watch N, with --fleet SPOOL_DIR): re-render the fleet
table every N seconds with an MFU / goodput / step-budget header —
a live top(1) over the telemetry spool.

Examples:
  python tools/tpustat.py --model mnist --steps 20 --json
  python tools/tpustat.py --model resnet --steps 10 --prom
  python tools/tpustat.py --model mnist --platform env   # real backend
  python tools/tpustat.py --fleet /run/spool --trace fleet.json
  python tools/tpustat.py --fleet --selftest --json      # CI gate
  python tools/tpustat.py --model mnist --slo --rules ci.rules
  python tools/tpustat.py --slo --selftest --json        # CI gate
  python tools/tpustat.py --fleet /run/spool --watch 5
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "benchmark", "fluid"))
sys.path.insert(0, os.path.join(_REPO, "tools"))

from proglint import ALL_MODELS, model_args  # noqa: E402


def build_model(name, args=None):
    """(main_program, startup_program, loss, feed_fn) — the proglint
    builder plus the model's synthetic feed generator, which tpustat
    needs to actually run the steps."""
    import paddle_tpu as fluid
    args = args or model_args()
    model_mod = __import__(f"models.{name}", fromlist=["get_model"])
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        with fluid.unique_name.guard():
            loss, feed_fn = model_mod.get_model(args)
            opt = fluid.optimizer.Adam(args.learning_rate) \
                if name == "machine_translation" \
                else fluid.optimizer.Momentum(args.learning_rate, 0.9)
            opt.minimize(loss)
    return main_p, startup_p, loss, feed_fn


def validate_metrics(snap, steps):
    """Structural checks over a telemetry snapshot from a `steps`-long
    cached run. Returns a list of problem strings (empty = healthy)."""
    problems = []

    def need(name):
        if name not in snap:
            problems.append(f"missing metric {name!r}")
            return None
        return snap[name]

    compiles = need("executor.compile_count")
    hits = need("executor.cache_hit_count") \
        if "executor.cache_hit_count" in snap else 0
    n_steps = need("executor.steps")
    for name, v in snap.items():
        if isinstance(v, dict):       # histogram
            bucket_total = sum(v.get("buckets", {}).values())
            if bucket_total != v.get("count"):
                problems.append(
                    f"histogram {name!r}: bucket total {bucket_total} "
                    f"!= count {v.get('count')}")
            if v.get("count", 0) < 0 or v.get("sum", 0) < 0:
                problems.append(f"histogram {name!r}: negative count/sum")
        elif isinstance(v, bool) or not isinstance(v, (int, float)):
            problems.append(f"metric {name!r}: non-numeric value {v!r}")
    if isinstance(compiles, int) and isinstance(n_steps, int):
        if n_steps != steps:
            problems.append(
                f"executor.steps {n_steps} != requested steps {steps}")
        if compiles + hits != steps:
            problems.append(
                f"compile_count {compiles} + cache_hit_count {hits} "
                f"!= steps {steps}")
        if compiles < 1:
            problems.append("no compile recorded")
    h = snap.get("executor.step_seconds")
    if isinstance(h, dict) and h.get("count") != steps:
        problems.append(
            f"executor.step_seconds count {h.get('count')} != {steps}")
    return problems


def _fmt_value(v):
    if isinstance(v, dict):
        m = f" mean={v['mean']:.4g}s max={v['max']:.4g}s" \
            if v.get("count") else ""
        return f"hist count={v['count']}{m}"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


# ------------------------------------------------------------------ fleet

def _fleet_worker(rank, spool):
    """Hidden mode: one local single-process 'rank' for the selftest —
    runs a tiny training loop with telemetry + fleet configured, records
    one instrumented collective and the pipeline bubble gauge, then
    flushes its rank snapshot to the spool. Rank 1 injects synthetic
    slow-step observations so the straggler detector has a
    deterministic culprit regardless of CI box load."""
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    import paddle_tpu as fluid
    from paddle_tpu import layers, telemetry
    from paddle_tpu.parallel import collective, pipeline

    telemetry.enable()
    telemetry.fleet.configure(rank=rank, world=2, spool_dir=spool)

    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        with fluid.unique_name.guard():
            x = layers.data("x", shape=[8])
            y = layers.data("y", shape=[4])
            pred = layers.fc(x, size=4)
            loss = layers.mean(layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup_p, feed={}, fetch_list=[])
    telemetry.reset()               # steady state: startup compile off
    telemetry.fleet.mark_clock()    # the shared-barrier marker analog

    rng = np.random.RandomState(rank)
    for _ in range(5):
        feed = {"x": rng.randn(8, 8).astype("float32"),
                "y": rng.randn(8, 4).astype("float32")}
        exe.run(main_p, feed=feed, fetch_list=[loss])

    # one collective through the instrumented wrappers (trace-time
    # accounting; a 1-device axis is enough for the counters)
    mesh = Mesh(np.array(jax.devices()[:1]), ("dp",))
    f = jax.jit(jax.shard_map(
        lambda v: collective.all_reduce(v, axis_name="dp"),
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False))
    np.asarray(f(jnp.ones((4, 8), jnp.float32)))

    # one int8 gradient sync through the gradsync policy layer, so the
    # fleet report's raw-vs-wire gauges have known per-rank values:
    # 512 f32 grads -> raw 2048 B, wire 512 B codes + 2 block scales
    # (8 B) = 520 B, ratio 2048/520
    from paddle_tpu.parallel import gradsync
    pol = gradsync.parse_policy("int8:ef=0")
    g2 = jax.jit(jax.shard_map(
        lambda v: gradsync.sync_gradients({"w": v}, {}, pol, dp=1)[0]["w"],
        mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False))
    np.asarray(g2(jnp.ones((64, 8), jnp.float32)))

    # pipeline bubble gauge via the same helper PipelineTrainer uses
    pipeline.record_bubble("gpipe", n_microbatch=4, n_stages=2)

    # sharded-embedding engine gauges with known values, so the fleet
    # merge of the embed columns is pinned (parallel/sparse.py writes
    # these per table; here the selftest plays the engine's role)
    telemetry.gauge("embed.big_table.rows").set(64)
    telemetry.gauge("embed.big_table.unique_ratio").set(0.5)
    telemetry.counter("embed.big_table.exchange_bytes").inc(4096)

    # request-trace exemplar gauges with known values — two completed
    # traces, one hedge-triggered, through the REAL reqtrace publish
    # path — so the fleet traces rollup (the tpustat --watch header
    # line) is pinned end to end
    telemetry.reqtrace_enable()
    rt = telemetry.reqtrace
    rt.trace_begin(f"w{rank}-hedged")
    rt.flag(f"w{rank}-hedged", "hedge")
    rt.trace_end(f"w{rank}-hedged")
    rt.trace_begin(f"w{rank}-plain")
    rt.trace_end(f"w{rank}-plain")

    if rank == 1:
        # synthetic straggler: this "host" reports pathologically slow
        # steps, so the detector path is exercised deterministically
        h = telemetry.histogram("executor.step_seconds")
        for _ in range(10):
            h.observe(2.0)

    path = telemetry.fleet.write_rank_snapshot()
    print(json.dumps({"rank": rank, "snapshot": path, "ok": True}))
    return 0


def _validate_fleet_report(rep, collector):
    """Structural checks over a merged fleet report (CI-gate grade)."""
    problems = []
    if len(rep["ranks"]) < 1:
        problems.append("no ranks in spool")
    for r in rep["ranks"]:
        pr = rep["per_rank"].get(str(r))
        if pr is None:
            problems.append(f"rank {r} missing from per_rank")
            continue
        if pr["step_seconds_mean"] is None:
            problems.append(f"rank {r}: no step timing")
    merged = rep["merged"]
    for name, ent in merged.items():
        if ent["kind"] == "histogram":
            v = ent["value"]
            if sum(v.get("buckets", {}).values()) != v.get("count"):
                problems.append(
                    f"merged histogram {name!r}: bucket total != count")
        elif ent["kind"] == "gauge":
            if len(ent.get("per_rank", {})) == 0:
                problems.append(f"merged gauge {name!r}: no per-rank "
                                "values retained")
    strag = rep.get("straggler") or {}
    if "verdict" not in strag:
        problems.append("no straggler verdict")
    try:
        trace = json.loads(json.dumps(collector.stitched_trace()))
        pids = {e.get("pid") for e in trace["traceEvents"]
                if e.get("ph") == "X"}
        if not pids.issuperset(set(rep["ranks"])):
            problems.append(
                f"stitched trace pids {sorted(pids)} do not cover "
                f"ranks {rep['ranks']}")
        for e in trace["traceEvents"]:
            if e.get("ph") == "X" and ("ts" not in e or "dur" not in e):
                problems.append("stitched X event missing ts/dur")
                break
    except (ValueError, KeyError) as e:
        problems.append(f"stitched trace does not round-trip: {e}")
    return problems


def _print_fleet_table(rep):
    strag = rep.get("straggler") or {}
    flagged = set(strag.get("flagged") or [])
    print(f"tpufleet: {len(rep['ranks'])} ranks "
          f"(declared process_count {rep['process_count']}), "
          f"verdict: {strag.get('verdict', '?')}")
    hdr = (f"  {'rank':<5} {'host':<12} {'steps':>5} {'step_ms':>9} "
           f"{'mfu%':>6} "
           f"{'coll#':>6} {'coll_KB':>8} {'bubble%':>8} "
           f"{'gs_raw_KB':>10} {'gs_wire_KB':>11} {'gs_x':>6} "
           f"{'emb_rows':>9} {'uniq%':>6} {'exch_KB':>8} "
           f"{'hbm_MB':>8} {'peak_MB':>8}  verdict")
    print(hdr)
    for r in rep["ranks"]:
        pr = rep["per_rank"][str(r)]
        mean = pr["step_seconds_mean"]
        bubble = pr["bubble_fraction"]
        ratio = pr.get("gradsync_ratio")
        uniq = pr.get("embed_unique_ratio")
        mfu = pr.get("mfu")
        hbm = pr.get("hbm_bytes")
        hbm_pk = pr.get("hbm_peak_bytes")
        print(f"  {r:<5} {str(pr.get('hostname') or '-')[:12]:<12} "
              f"{pr['steps']:>5} "
              f"{(mean * 1e3 if mean else 0):>9.2f} "
              f"{(f'{mfu * 100:.1f}' if mfu else '-'):>6} "
              f"{pr['collective_calls']:>6} "
              f"{pr['collective_bytes'] / 1024:>8.1f} "
              f"{(bubble * 100 if bubble is not None else 0):>8.1f} "
              f"{pr.get('gradsync_raw_bytes', 0) / 1024:>10.1f} "
              f"{pr.get('gradsync_wire_bytes', 0) / 1024:>11.1f} "
              f"{(f'{ratio:.2f}' if ratio else '-'):>6} "
              f"{pr.get('embed_rows', 0):>9} "
              f"{(f'{uniq * 100:.1f}' if uniq is not None else '-'):>6} "
              f"{pr.get('embed_exchange_bytes', 0) / 1024:>8.1f} "
              f"{(f'{hbm / 1e6:.1f}' if hbm else '-'):>8} "
              f"{(f'{hbm_pk / 1e6:.1f}' if hbm_pk else '-'):>8}  "
              f"{'STRAGGLER' if r in flagged else 'ok'}")
    if rep["collectives"]:
        parts = [f"{op} x{d.get('count', 0)} "
                 f"({d.get('bytes', 0) / 1024:.1f} KB)"
                 for op, d in sorted(rep["collectives"].items())]
        print("  collectives (trace-time): " + ", ".join(parts))
    _print_replica_table(rep)
    if strag.get("hint"):
        print(f"  hint: {strag['hint']}")


# serving.replica.<i>.guard_state gauge codes (guard/health.py
# STATE_CODES) — rendered in the replica table's state column
_GUARD_STATES = {0.0: "ok", 1.0: "probation", 2.0: "EJECTED",
                 3.0: "half-open"}

# scale.last_decision gauge -> label (serving.scale DECISION_CODES)
_SCALE_DECISIONS = {0.0: "hold", 1.0: "up", 2.0: "down",
                    3.0: "ceiling", 4.0: "rejected", 5.0: "cooldown"}


def _print_replica_table(rep):
    """Serving-farm sub-table: one row per decode replica, from the
    serving.replica.<i>.* gauges (ranks serving no farm print
    nothing), plus one guard line per rank running overload defense
    (serving.guard.* rollups) and one autoscaler line per rank with a
    live ScaleController (scale.* rollups: target vs live, last
    decision + triggering rule, cooldown remaining)."""
    rows = []
    for r in rep["ranks"]:
        pr = rep["per_rank"][str(r)]
        for idx, d in sorted(
                (pr.get("serving_replicas") or {}).items(),
                key=lambda kv: int(kv[0]) if kv[0].isdigit() else 0):
            rows.append((r, idx, d))
    if not rows:
        return
    print(f"  serving replicas: {len(rows)}")
    print(f"    {'rank':<5} {'rep':>3} {'ver':>4} {'slots':>7} "
          f"{'queue':>6} {'kv_MB':>7} {'tokens':>8} {'tok/s':>8} "
          f"{'restarts':>8}  state")
    for r, idx, d in rows:
        state = "down" if not d.get("alive", 1.0) else (
            "draining" if d.get("draining") else "ok")
        if state == "ok" and "guard_state" in d:
            state = _GUARD_STATES.get(d["guard_state"], "ok")
        print(f"    {r:<5} {idx:>3} {int(d.get('version', 1)):>4} "
              f"{int(d.get('slots_in_use', 0)):>3}/"
              f"{int(d.get('num_slots', 0)):<3} "
              f"{int(d.get('queue_depth', 0)):>6} "
              f"{d.get('kv_cache_bytes', 0) / 1e6:>7.2f} "
              f"{int(d.get('tokens_total', 0)):>8} "
              f"{d.get('goodput_tps', 0.0):>8.1f} "
              f"{int(d.get('restarts', 0)):>8}  {state}")
    for r in rep["ranks"]:
        g = rep["per_rank"][str(r)].get("serving_guard") or {}
        if not g:
            continue
        p99 = g.get("p99_ms")
        print(f"    guard[rank {r}]: "
              f"{'BROWNOUT' if g.get('brownout') else 'normal'} "
              f"ejections={int(g.get('ejections', 0))} "
              f"readmissions={int(g.get('readmissions', 0))} "
              f"hedges={int(g.get('hedges', 0))} "
              f"(wins={int(g.get('hedge_wins', 0))}) "
              f"resubmits={int(g.get('resubmits', 0))} "
              f"sheds={int(g.get('brownout_sheds', 0))} "
              f"p99={f'{p99:.1f}ms' if p99 is not None else '-'}")
    for r in rep["ranks"]:
        s = rep["per_rank"][str(r)].get("serving_scale") or {}
        if not s:
            continue
        dec = _SCALE_DECISIONS.get(s.get("last_decision", 0.0),
                                   "hold")
        rule = s.get("last_rule", -1.0)
        if rule is not None and rule >= 0:
            dec = f"{dec}(rule#{int(rule)})"
        cool = s.get("cooldown_remaining_s", 0.0) or 0.0
        print(f"    scale[rank {r}]: "
              f"target={int(s.get('target_replicas', 0))} "
              f"live={int(s.get('live_replicas', 0))} "
              f"last={dec} "
              f"cooldown={cool:.1f}s "
              f"{'AT-CEILING' if s.get('at_ceiling') else 'headroom'} "
              f"free_dev={int(s.get('free_devices', 0))} "
              f"ups={int(s.get('ups', 0))} "
              f"downs={int(s.get('downs', 0))}")


def _fleet_report(spool, as_json, trace_path):
    """tpustat --fleet SPOOL_DIR: merge the rank spool and report."""
    from paddle_tpu.telemetry import fleet as tfleet
    coll = tfleet.FleetCollector()
    try:
        coll.collect(spool)
    except (OSError, ValueError) as e:
        print(f"tpustat --fleet: {e}", file=sys.stderr)
        return 2
    rep = coll.report()
    problems = _validate_fleet_report(rep, coll)
    if trace_path:
        with open(trace_path, "w") as f:
            json.dump(coll.stitched_trace(), f)
    if as_json:
        print(json.dumps(dict(rep, problems=problems,
                              ok=not problems), default=str))
    else:
        _print_fleet_table(rep)
        if trace_path:
            print(f"  stitched trace: {trace_path}")
        for prob in problems:
            print(f"MALFORMED: {prob}", file=sys.stderr)
    return 2 if problems else 0


def _fleet_selftest(as_json, trace_path):
    """tpustat --fleet --selftest: spawn 2 local worker subprocesses,
    merge their spool, validate the merged snapshot + stitched trace.
    Exit 0 iff everything is well-formed — the tier-1 CI gate."""
    import subprocess
    import tempfile
    spool = tempfile.mkdtemp(prefix="tpufleet_selftest_")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("PADDLE_TPU_TELEMETRY", "PADDLE_TPU_TELEMETRY_DIR",
              "PADDLE_TPU_FLEET_RANK", "PADDLE_TPU_FLEET_WORLD",
              "PADDLE_TPU_FLEET_DIR", "XLA_FLAGS"):
        env.pop(k, None)
    me = os.path.abspath(__file__)
    problems = []
    logs, procs = [], []
    for r in (0, 1):
        log = os.path.join(spool, f"worker{r}.log")
        logs.append(log)
        with open(log, "w") as lf:
            procs.append(subprocess.Popen(
                [sys.executable, me, "--fleet-worker", str(r),
                 "--spool", spool],
                stdout=lf, stderr=subprocess.STDOUT, env=env,
                cwd=_REPO))
    for r, p in enumerate(procs):
        try:
            rc = p.wait(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
            rc = -9
        if rc != 0:
            tail = open(logs[r]).read()[-1200:]
            problems.append(f"worker {r} rc={rc}: {tail}")

    from paddle_tpu.telemetry import fleet as tfleet
    rep, strag = {}, {}
    if not problems:
        coll = tfleet.FleetCollector()
        try:
            coll.collect(spool)
            rep = coll.report()
            strag = rep["straggler"]
            problems += _validate_fleet_report(rep, coll)
            # the selftest knows exactly what the workers did — pin it
            if rep["ranks"] != [0, 1]:
                problems.append(f"expected ranks [0, 1], got "
                                f"{rep['ranks']}")
            # per worker: one fp32 all_reduce (4x8 f32 = 128 B) plus
            # one int8 gradsync all_reduce (512 codes + 2 fp32 block
            # scales = 520 B)
            ar = rep["merged"].get("collective.all_reduce.count")
            if not ar or ar["value"] != 4:
                problems.append(
                    f"merged collective.all_reduce.count != 4: {ar}")
            ab = rep["merged"].get("collective.all_reduce.bytes")
            if not ab or ab["value"] != 2 * (128 + 520):
                problems.append(
                    f"merged collective.all_reduce.bytes != 1296: {ab}")
            # gradsync gauges must merge correctly across ranks:
            # counters sum, the per-rank compression ratio is retained
            graw = rep["merged"].get("gradsync.raw_bytes")
            if not graw or graw["value"] != 2 * 2048:
                problems.append(
                    f"merged gradsync.raw_bytes != 4096: {graw}")
            gwire = rep["merged"].get("gradsync.wire_bytes")
            if not gwire or gwire["value"] != 2 * 520:
                problems.append(
                    f"merged gradsync.wire_bytes != 1040: {gwire}")
            gratio = rep["merged"].get("gradsync.compression_ratio")
            expect_ratio = 2048 / 520
            if (not gratio or gratio["kind"] != "gauge"
                    or sorted(gratio.get("per_rank", {})) != ["0", "1"]
                    or any(abs(v - expect_ratio) > 1e-6
                           for v in gratio["per_rank"].values())):
                problems.append(
                    f"merged gradsync.compression_ratio malformed: "
                    f"{gratio}")
            for r in (0, 1):
                pr = rep["per_rank"][str(r)]
                if pr.get("gradsync_raw_bytes") != 2048 \
                        or pr.get("gradsync_wire_bytes") != 520:
                    problems.append(
                        f"rank {r} gradsync raw/wire bytes wrong: "
                        f"{pr.get('gradsync_raw_bytes')}/"
                        f"{pr.get('gradsync_wire_bytes')}")
            for r in (0, 1):
                bub = rep["per_rank"][str(r)]["bubble_fraction"]
                if bub is None or abs(bub - 0.2) > 1e-9:
                    problems.append(
                        f"rank {r} bubble_fraction != 0.2: {bub}")
            # sharded-embedding columns: per-rank rollup (rows 64,
            # unique 0.5, 4096 exchange bytes) + table detail + the
            # counter summing across ranks in the merge
            for r in (0, 1):
                pr = rep["per_rank"][str(r)]
                if pr.get("embed_rows") != 64 \
                        or pr.get("embed_unique_ratio") != 0.5 \
                        or pr.get("embed_exchange_bytes") != 4096:
                    problems.append(
                        f"rank {r} embed columns wrong: "
                        f"{pr.get('embed_rows')}/"
                        f"{pr.get('embed_unique_ratio')}/"
                        f"{pr.get('embed_exchange_bytes')}")
                det = pr.get("embed_tables", {}).get("big_table", {})
                if det.get("rows") != 64:
                    problems.append(
                        f"rank {r} embed_tables detail wrong: {det}")
            ex = rep["merged"].get("embed.big_table.exchange_bytes")
            if not ex or ex["value"] != 2 * 4096:
                problems.append(
                    f"merged embed exchange_bytes != 8192: {ex}")
            if strag.get("flagged") != [1]:
                problems.append(
                    f"straggler detector should flag rank 1, got "
                    f"{strag.get('flagged')}")
            # request-trace rollup: each worker completed 2 traces,
            # 1 hedge-triggered (serving.trace.* gauges from the
            # reqtrace publish path), and the --watch header renders
            # the fleet-wide traces line from them
            for r in (0, 1):
                t = rep["per_rank"][str(r)].get("serving_traces") or {}
                if (t.get("seen"), t.get("kept"),
                        t.get("trigger.hedge")) != (2, 1, 1):
                    problems.append(
                        f"rank {r} serving_traces wrong: {t}")
            if "traces: 2/4 kept (hedge=2)" not in _watch_header(rep):
                problems.append(
                    "watch header is missing the traces rollup line")
            st = coll.stitched_trace()
            if st["fleetAlignment"] != "marker":
                problems.append(
                    f"expected marker clock alignment, got "
                    f"{st['fleetAlignment']}")
            # idempotent re-merge: same spool again, same totals —
            # and the traces line (gauges, not counters) must not
            # double when the same rank envelopes land twice
            coll.collect(spool)
            rep2 = coll.report()
            ar2 = rep2["merged"]["collective.all_reduce.count"]
            if ar2["value"] != 4:
                problems.append(
                    f"re-merge not idempotent: count {ar2['value']}")
            if "traces: 2/4 kept (hedge=2)" not in _watch_header(rep2):
                problems.append(
                    "traces rollup not idempotent on re-merge")
            if trace_path:
                with open(trace_path, "w") as f:
                    json.dump(st, f)
        except (OSError, ValueError, KeyError) as e:
            problems.append(f"collect/report failed: "
                            f"{type(e).__name__}: {e}")

    result = {"selftest": "fleet", "spool": spool,
              "ranks": rep.get("ranks"),
              "straggler": strag.get("verdict"),
              "problems": problems, "ok": not problems}
    if as_json:
        print(json.dumps(result, default=str))
    else:
        if rep:
            _print_fleet_table(rep)
        for prob in problems:
            print(f"SELFTEST FAIL: {prob}", file=sys.stderr)
        if not problems:
            print("fleet selftest OK")
    return 2 if problems else 0


# ------------------------------------------------------------ slo / watch

def _default_history_path():
    return os.path.join(_REPO, "BENCH_history.jsonl")


def _load_rules(rules_arg):
    """--rules: a file of one rule per line (# comments) or an inline
    ';'-separated list; default ruleset otherwise."""
    from paddle_tpu.telemetry import slo
    if not rules_arg:
        return list(slo.DEFAULT_RULES)
    if os.path.exists(rules_arg):
        with open(rules_arg) as f:
            lines = f.read().splitlines()
    else:
        lines = rules_arg.split(";")
    return [ln.strip() for ln in lines
            if ln.strip() and not ln.strip().startswith("#")]


def _slo_gate(snap, rules_arg, history_path, platform=None):
    """Evaluate rules against `snap` + regression-gate the history
    spine. Returns (problems, detail_dict)."""
    from paddle_tpu.telemetry import slo
    problems = []
    rules = _load_rules(rules_arg)
    try:
        report = slo.evaluate(rules, snap=snap)
    except ValueError as e:
        return [f"bad SLO rule: {e}"], {}
    for r in report.violations:
        problems.append(f"SLO violated: {r.rule.text} "
                        f"(observed {r.observed:g})")
    history_path = history_path or _default_history_path()
    records = slo.load_history(history_path)
    gate = slo.history_gate(records, platform=platform)
    for reg in gate["regressions"]:
        problems.append(
            f"perf regression: {reg['metric']} = {reg['current']:g} "
            f"vs rolling median {reg['median']:g} "
            f"(threshold {reg['threshold']:g}, n={reg['n']})")
    detail = {"slo": report.to_dict(),
              "history": {"path": history_path,
                          "records": len(records),
                          "checked": gate["checked"],
                          "regressions": gate["regressions"]}}
    return problems, detail


def _slo_selftest(as_json, history_path):
    """tpustat --slo --selftest: validate the tpuscope layer end to end
    in-process — live MFU/goodput gauges on a tiny model, rule parsing,
    and the regression gate flagging an injected step-time regression.
    Exit 0 iff everything holds — the tier-1 CI gate."""
    import tempfile
    problems = []

    # 1) rule parsing round-trips (aliases, stats, operators)
    from paddle_tpu.telemetry import slo
    r = slo.parse_rule("step_ms.p99 < 250")
    if (r.metric, r.stat, r.scale, r.threshold) != \
            ("executor.step_seconds", "p99", 1e3, 250.0):
        problems.append(f"rule parse wrong: {r.metric}/{r.stat}/"
                        f"{r.scale}/{r.threshold}")
    r = slo.parse_rule("perf.mfu > 0.3")
    if (r.metric, r.stat) != ("perf.mfu", "value"):
        problems.append(f"dotted metric parse wrong: "
                        f"{r.metric}/{r.stat}")
    try:
        slo.parse_rule("nonsense ~ 3")
        problems.append("bad rule did not raise")
    except ValueError:
        pass

    # 2) live gauges: a tiny training loop must produce perf.mfu > 0
    # (synthetic peak: CPU has no table entry) and pass generous rules
    os.environ["PADDLE_TPU_PEAK_FLOPS"] = "1e12"
    try:
        import numpy as np
        import paddle_tpu as fluid
        from paddle_tpu import layers, telemetry
        telemetry.enable()
        main_p, startup_p = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_p, startup_p):
            with fluid.unique_name.guard():
                x = layers.data("x", shape=[8])
                y = layers.data("y", shape=[4])
                pred = layers.fc(x, size=4)
                loss = layers.mean(
                    layers.square_error_cost(pred, y))
                fluid.optimizer.SGD(0.1).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup_p, feed={}, fetch_list=[])
        telemetry.reset()
        rng = np.random.RandomState(0)
        for _ in range(6):
            feed = {"x": rng.randn(8, 8).astype("float32"),
                    "y": rng.randn(8, 4).astype("float32")}
            exe.run(main_p, feed=feed, fetch_list=[loss])
        snap = telemetry.snapshot()
        live = slo.evaluate(list(slo.DEFAULT_RULES)
                            + ["perf.mfu > 0",
                               "perf.goodput.examples_per_s > 0",
                               "executor.steps >= 5"], snap=snap)
        if not live.ok:
            problems.append("live rules failed:\n" + str(live))
        mfu = snap.get("perf.mfu")
        if not mfu or mfu <= 0:
            problems.append(f"perf.mfu gauge not live: {mfu}")
    finally:
        os.environ.pop("PADDLE_TPU_PEAK_FLOPS", None)

    # 3) the regression gate MUST flag injected regressions and MUST
    # pass a clean series (both directions)
    if not slo.check_regression([10.0] * 8, 100.0,
                                direction="lower")["regressed"]:
        problems.append("injected step-time regression not flagged")
    if not slo.check_regression([1000.0] * 8, 100.0,
                                direction="higher")["regressed"]:
        problems.append("injected throughput regression not flagged")
    if slo.check_regression([10.0, 10.1, 9.9, 10.0, 10.2], 10.1,
                            direction="lower")["regressed"]:
        problems.append("clean step-time series falsely flagged")

    # 4) history spine: append/load round-trip + end-to-end gate over
    # a file with one injected step-time regression
    with tempfile.TemporaryDirectory(prefix="tpuslo_") as td:
        hist = os.path.join(td, "hist.jsonl")
        base = {"schema": slo.HISTORY_SCHEMA, "platform": "cpu",
                "unit": "ms", "stage": "deepfm"}
        recs = [dict(base, metric="deepfm_step_ms", value=10.0 + 0.01 * i)
                for i in range(8)]
        recs.append(dict(base, metric="deepfm_step_ms", value=100.0))
        slo.append_history(hist, recs)
        loaded = slo.load_history(hist)
        if len(loaded) != len(recs):
            problems.append(f"history round-trip lost records: "
                            f"{len(loaded)} != {len(recs)}")
        gate = slo.history_gate(loaded)
        if gate["ok"] or not any(
                g["metric"] == "deepfm_step_ms"
                for g in gate["regressions"]):
            problems.append(
                f"history gate missed the injected step-time "
                f"regression: {gate}")
        # clean spine passes
        clean = [dict(base, metric="deepfm_step_ms",
                      value=10.0 + 0.01 * i) for i in range(9)]
        if not slo.history_gate(clean)["ok"]:
            problems.append("history gate flagged a clean series")

    result = {"selftest": "slo", "problems": problems,
              "ok": not problems}
    if as_json:
        print(json.dumps(result, default=str))
    else:
        for prob in problems:
            print(f"SELFTEST FAIL: {prob}", file=sys.stderr)
        if not problems:
            print("slo selftest OK")
    return 2 if problems else 0


_BUDGET_HISTS = (
    ("feed_put", "executor.feed_put_seconds"),
    ("dispatch", "executor.step_seconds"),
    ("stall", "executor.pending_wait_seconds"),
    ("readback", "executor.fetch_readback_seconds"),
    ("check", "executor.finite_check_seconds"),
)


def _merged_value(merged, name):
    ent = merged.get(name)
    return ent.get("value") if isinstance(ent, dict) else None


def _watch_header(rep):
    """The mfu / goodput / step-budget summary lines above the fleet
    table in --watch mode."""
    from paddle_tpu.telemetry import registry
    merged = rep.get("merged", {})
    mfus = [pr["mfu"] for pr in rep.get("per_rank", {}).values()
            if pr.get("mfu")]
    goodput = [pr["goodput_examples_per_s"]
               for pr in rep.get("per_rank", {}).values()
               if pr.get("goodput_examples_per_s")]
    step_h = _merged_value(merged, "executor.step_seconds")
    p99 = registry.quantile_from_buckets(step_h, 0.99) \
        if isinstance(step_h, dict) else None
    lines = [
        "  mfu: " + (f"{sum(mfus) / len(mfus) * 100:.1f}% (mean of "
                     f"{len(mfus)} ranks)" if mfus else "n/a")
        + "   goodput: "
        + (f"{sum(goodput):.1f} examples/s" if goodput else "n/a")
        + "   step p99: "
        + (f"{p99 * 1e3:.2f} ms" if p99 else "n/a")]
    sums = []
    for label, name in _BUDGET_HISTS:
        v = _merged_value(merged, name)
        sums.append((label, float(v.get("sum", 0.0))
                     if isinstance(v, dict) else 0.0))
    total = sum(s for _, s in sums)
    if total > 0:
        width = 24
        parts = []
        for label, s in sums:
            if s <= 0:
                continue
            bar = "#" * max(1, round(s / total * width))
            parts.append(f"{label} {s / total * 100:4.1f}% {bar}")
        lines.append("  step budget: " + "  ".join(parts))
    # request-trace exemplar pressure: sum of the per-rank
    # serving.trace.* gauges (fleet rollup). Gauges, so the line is
    # stable when the same spool is merged twice.
    tr = [pr.get("serving_traces") or {}
          for pr in rep.get("per_rank", {}).values()]
    tr = [t for t in tr if t]
    if tr:
        seen = sum(int(t.get("seen", 0)) for t in tr)
        kept = sum(int(t.get("kept", 0)) for t in tr)
        mix = {}
        for t in tr:
            for k, v in t.items():
                if k.startswith("trigger."):
                    name = k[len("trigger."):]
                    mix[name] = mix.get(name, 0) + int(v)
        mixs = " ".join(f"{k}={v}" for k, v in sorted(mix.items()))
        lines.append(f"  traces: {kept}/{seen} kept"
                     + (f" ({mixs})" if mixs else ""))
    # memory-ledger rollup (PR-20): worst rank's live and peak HBM,
    # from the per-rank memledger.* / device.* gauges
    hbms = [(int(pr.get("hbm_bytes") or 0),
             int(pr.get("hbm_peak_bytes") or 0), r)
            for r, pr in rep.get("per_rank", {}).items()
            if pr.get("hbm_bytes") or pr.get("hbm_peak_bytes")]
    if hbms:
        cur, pk, worst = max(hbms, key=lambda t: t[1] or t[0])
        lines.append(f"  hbm: {cur / 1e6:.1f} MB live, "
                     f"{pk / 1e6:.1f} MB peak "
                     f"(worst rank {worst}, {len(hbms)} reporting)")
    return "\n".join(lines)


def _watch(spool, interval, iterations, as_json):
    """tpustat --fleet SPOOL --watch N: re-render the fleet view every
    N seconds. `iterations` bounds the loop (None = forever)."""
    import time as _time
    from paddle_tpu.telemetry import fleet as tfleet
    i = 0
    while True:
        coll = tfleet.FleetCollector()
        err = None
        rep = None
        try:
            coll.collect(spool)
            rep = coll.report()
        except (OSError, ValueError) as e:
            err = f"{type(e).__name__}: {e}"
        if as_json:
            out = {"iteration": i, "ok": err is None}
            if rep:
                out["ranks"] = rep["ranks"]
                out["per_rank"] = rep["per_rank"]
            if err:
                out["error"] = err
            print(json.dumps(out, default=str), flush=True)
        else:
            if sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")   # clear + home
            print(f"tpustat --watch (every {interval:g}s, "
                  f"iteration {i})")
            if err:
                print(f"  spool not readable yet: {err}")
            else:
                print(_watch_header(rep))
                _print_fleet_table(rep)
            sys.stdout.flush()
        i += 1
        if iterations is not None and i >= iterations:
            return 0
        _time.sleep(interval)


def main(argv=None):
    p = argparse.ArgumentParser(
        description="runtime telemetry over a benchmark model")
    p.add_argument("--model", default="mnist", choices=ALL_MODELS)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--async-steps", type=int, default=0,
                   help="run the step loop through the tpupipe async "
                        "window (Executor.run(async_steps=K)); 0 = "
                        "synchronous")
    p.add_argument("--platform", default="cpu",
                   help="JAX_PLATFORMS to force before backend init "
                        "('env' keeps the environment's value; default "
                        "cpu so the CLI never hangs on a down relay)")
    p.add_argument("--trace", metavar="PATH", default=None,
                   help="Chrome trace output "
                        "(default /tmp/tpustat_<model>.trace.json)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one machine-readable JSON line; exit non-zero "
                        "on malformed metrics")
    p.add_argument("--prom", action="store_true",
                   help="also print the Prometheus text exposition")
    p.add_argument("--profile-device", action="store_true",
                   help="run a short device trace and merge per-op "
                        "device times onto the timeline (needs a "
                        "backend whose xplane layout we can decode)")
    p.add_argument("--fleet", nargs="?", const="", default=None,
                   metavar="SPOOL_DIR",
                   help="fleet mode: merge a telemetry.fleet rank "
                        "spool and print per-rank step time, "
                        "collective volume, bubble %%, and the "
                        "straggler verdict (--trace writes the "
                        "stitched multi-rank timeline)")
    p.add_argument("--selftest", action="store_true",
                   help="with --fleet or --slo: validate the layer "
                        "end to end (CI gate)")
    p.add_argument("--slo", action="store_true",
                   help="evaluate SLO rules against the run's metrics "
                        "and regression-gate BENCH_history.jsonl; "
                        "exit 2 on violation (tpuscope)")
    p.add_argument("--rules", default=None,
                   help="SLO rules: a file (one per line, # comments) "
                        "or an inline ';'-separated list; default: "
                        "telemetry.slo.DEFAULT_RULES")
    p.add_argument("--history", default=None, metavar="PATH",
                   help="perf-history spine for the --slo regression "
                        "gate (default <repo>/BENCH_history.jsonl)")
    p.add_argument("--watch", type=float, default=None, metavar="N",
                   help="with --fleet SPOOL_DIR: re-render the fleet "
                        "view every N seconds (mfu / goodput / step "
                        "budget header)")
    p.add_argument("--watch-iterations", type=int, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--fleet-worker", type=int, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--spool", default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.platform != "env":
        os.environ["JAX_PLATFORMS"] = args.platform

    if args.fleet_worker is not None:
        return _fleet_worker(args.fleet_worker, args.spool)
    if args.selftest and args.fleet is None and not args.slo:
        p.error("--selftest needs --fleet or --slo")
    if args.slo and args.selftest:
        return _slo_selftest(args.as_json, args.history)
    if args.watch is not None and args.fleet in (None, ""):
        p.error("--watch needs --fleet SPOOL_DIR")
    if args.fleet is not None:
        if args.selftest:
            return _fleet_selftest(args.as_json, args.trace)
        if not args.fleet:
            p.error("--fleet needs a SPOOL_DIR (or --selftest)")
        if args.watch is not None:
            return _watch(args.fleet, args.watch,
                          args.watch_iterations, args.as_json)
        return _fleet_report(args.fleet, args.as_json, args.trace)

    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu import telemetry

    telemetry.enable()
    main_p, startup_p, loss, feed_fn = build_model(
        args.model, model_args(batch_size=args.batch_size))
    exe = fluid.Executor()
    exe.run(startup_p, feed={}, fetch_list=[])
    # scope the metrics to the steady-state loop: the startup compile
    # is one-off noise next to `steps` worth of hit/miss accounting
    telemetry.reset()

    rng = np.random.RandomState(0)
    losses = []
    inflight_peak = 0
    if args.async_steps > 0:
        # pipelined loop: dispatch every step, consume at the end so
        # the window actually fills (consuming per-step would drain it)
        handles = []
        for _ in range(args.steps):
            feed = feed_fn(args.batch_size, rng)
            handles.append(exe.run(main_p, feed=feed,
                                   fetch_list=[loss],
                                   async_steps=args.async_steps))
            inflight_peak = max(inflight_peak, exe.inflight)
        exe.drain()
        losses = [float(np.asarray(h[0]).ravel()[0]) for h in handles]
    else:
        for _ in range(args.steps):
            feed = feed_fn(args.batch_size, rng)
            out = exe.run(main_p, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(out[0]).ravel()[0]))

    device_profile = None
    if args.profile_device:
        from paddle_tpu import profiler
        feed = feed_fn(args.batch_size, rng)
        try:
            per_step, ops = profiler.profile_step_fn(
                lambda: exe.run(main_p, feed=feed, fetch_list=[loss]),
                steps=3)
            device_profile = {"device_step_seconds": per_step,
                              "top_ops": dict(sorted(
                                  ops.items(),
                                  key=lambda kv: -kv[1])[:10])}
        except Exception as e:
            device_profile = {"error": f"{type(e).__name__}: {e}"}

    snap = telemetry.snapshot()
    problems = validate_metrics(snap, args.steps)

    trace_path = args.trace or f"/tmp/tpustat_{args.model}.trace.json"
    telemetry.write_chrome_trace(trace_path)
    try:
        with open(trace_path) as f:
            trace = json.loads(f.read())
        span_events = sum(1 for e in trace.get("traceEvents", [])
                          if e.get("ph") == "X")
        for e in trace.get("traceEvents", []):
            if e.get("ph") == "X" and ("ts" not in e or "dur" not in e):
                problems.append("trace X event missing ts/dur")
                break
        if span_events < args.steps:
            problems.append(
                f"trace has {span_events} span events < steps "
                f"{args.steps}")
    except (OSError, ValueError) as e:
        span_events = 0
        problems.append(f"trace does not round-trip: {e}")

    import jax
    # signature explosion at a glance: distinct compiled signatures
    # across the executor and inference engines (each gauge is set at
    # compile time — see executor.run / InferenceEngine._get_fn)
    signatures = int(max(snap.get("executor.signature_count", 0),
                         snap.get("inference.signature_count", 0)))
    slo_detail = None
    if args.slo:
        slo_problems, slo_detail = _slo_gate(
            snap, args.rules, args.history,
            platform=jax.devices()[0].platform)
        problems += slo_problems

    from paddle_tpu import diagnostics
    diag = diagnostics.status()
    result = {
        "model": args.model,
        "steps": args.steps,
        "batch_size": args.batch_size,
        "platform": jax.devices()[0].platform,
        "diagnostics": diag,
        "signatures": signatures,
        "async_steps": args.async_steps,
        "inflight_peak": inflight_peak,
        "final_loss": losses[-1] if losses else None,
        "metrics": snap,
        "trace": {"path": trace_path, "span_events": span_events},
        "problems": problems,
        "ok": not problems,
    }
    if device_profile is not None:
        result["device_profile"] = device_profile
    if slo_detail is not None:
        result["slo"] = slo_detail

    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        async_hdr = (f"async={args.async_steps} "
                     f"inflight_peak={inflight_peak} "
                     if args.async_steps > 0 else "")
        print(f"tpustat: {args.model} x {args.steps} steps "
              f"(batch {args.batch_size}) on "
              f"{result['platform']}, {signatures} compiled "
              f"signature{'s' if signatures != 1 else ''}, "
              f"{async_hdr}"
              f"nan_check={'on' if diag['nan_check'] else 'off'} "
              f"flight_recorder="
              f"{'on' if diag['flight_recorder'] else 'off'}")
        width = max((len(k) for k in snap), default=10)
        for name in sorted(snap):
            print(f"  {name:<{width}}  {_fmt_value(snap[name])}")
        print(f"trace: {trace_path} ({span_events} span events)")
        if device_profile:
            print(f"device profile: {device_profile}")
        for prob in problems:
            print(f"MALFORMED: {prob}", file=sys.stderr)
    if args.prom:
        print(telemetry.prometheus_text(), end="")
    return 2 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
