"""On-chip A/B for the flash-attention bf16 softmax escape (VERDICT r3
#2/#3): measures causal fwd+bwd wall time and attn-MFU at long context
with the in-kernel probability exp in f32 (exact flash algorithm) vs
bf16 (VPU-pressure escape), plus max|Δ| of outputs and grads between
the two — the validation the r3 note said was missing.

Run on the real chip:  python tools/flash_ab.py [--seqlens 8192,32768]
"""
import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def measure(T, dtype_name, repeats=3, inner=5):
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa
    import bench

    B, H, D = 1, 8, 64
    rng = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rng.randn(B, H, T, D).astype("float32"),
                           jnp.bfloat16) for _ in range(3)]
    p_dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32

    # CPU smoke: force the Pallas interpreter when the real kernel
    # can't run (non-TPU backend); on the chip this stays False
    use_pallas, interpret = fa.active()
    interpret = interpret or not use_pallas

    def loss_fn(q, k, v):
        out = fa.flash_attention(q, k, v, causal=True,
                                 softmax_dtype=p_dtype,
                                 interpret=interpret)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1, 2)))
    val, grads = g(q, k, v)
    np.asarray(grads[0][0, 0, 0])  # completion barrier through the relay
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(inner):
            val, grads = g(q, k, v)
        np.asarray(grads[0][0, 0, 0])
        times.append((time.perf_counter() - t0) / inner)
    dt = sorted(times)[len(times) // 2]
    fl = 12 * B * H * T * T * D * 0.5   # causal fwd+bwd matmul flops
    peak = bench._peak_flops(jax.devices()[0])  # None on CPU smoke
    return {"ms": round(dt * 1e3, 2),
            "attn_mfu": round(fl / dt / peak, 4) if peak else None,
            "out": val, "grads": grads}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqlens", default="8192,32768")
    args = ap.parse_args()
    import numpy as np

    report = {}
    for T in [int(s) for s in args.seqlens.split(",")]:
        f32 = measure(T, "f32")
        b16 = measure(T, "bf16")
        dg = max(float(np.max(np.abs(
            np.asarray(a, dtype=np.float32) -
            np.asarray(b, dtype=np.float32))))
            for a, b in zip(f32["grads"], b16["grads"]))
        report[f"T{T}"] = {
            "f32_ms": f32["ms"], "f32_attn_mfu": f32["attn_mfu"],
            "bf16_ms": b16["ms"], "bf16_attn_mfu": b16["attn_mfu"],
            "speedup": round(f32["ms"] / b16["ms"], 3),
            "loss_rel_delta": abs(float(f32["out"]) - float(b16["out"]))
            / max(abs(float(f32["out"])), 1e-9),
            "grad_max_abs_delta": dg,
        }
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
