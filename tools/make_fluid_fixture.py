"""Generate tests/fixtures/fluid_fc_model — a model directory in the
reference's on-disk inference-model format (binary protobuf `__model__`
+ one LoDTensor-stream file per parameter), as real Fluid's
save_inference_model would lay it out
(/root/reference/python/paddle/fluid/io.py, framework.proto,
lod_tensor.cc:245).

Deliberately does NOT use paddle_tpu.core.fluid_proto: the ProgramDesc
bytes come from the OFFICIAL protobuf runtime (protoc-compiled
framework.proto) and the tensor streams from explicit struct packing,
so the fixture is an independent witness the interop code is tested
against, not a product of it.

Usage: python tools/make_fluid_fixture.py
"""
import os
import struct
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROTO = "/root/reference/paddle/fluid/framework/framework.proto"
OUT = os.path.join(REPO, "tests", "fixtures", "fluid_fc_model")


def compile_proto(tmp):
    import shutil
    shutil.copy(PROTO, os.path.join(tmp, "framework.proto"))
    subprocess.run(["protoc", f"--python_out={tmp}", f"-I{tmp}",
                    os.path.join(tmp, "framework.proto")], check=True)
    sys.path.insert(0, tmp)
    import framework_pb2
    return framework_pb2


def write_ref_lod_tensor(path, arr):
    """tensor_util.cc TensorToStream layout, packed by hand."""
    arr = np.ascontiguousarray(arr)
    dt = {"float32": 5, "float64": 6, "int64": 3, "int32": 2}[str(arr.dtype)]
    # TensorDesc proto by hand: field1 varint data_type, field2 dims
    desc = bytes([0x08, dt])
    for d in arr.shape:
        desc += bytes([0x10]) + _varint(d)
    with open(path, "wb") as f:
        f.write(struct.pack("<I", 0))   # LoDTensor version
        f.write(struct.pack("<Q", 0))   # lod_level = 0
        f.write(struct.pack("<I", 0))   # Tensor version
        f.write(struct.pack("<i", len(desc)))
        f.write(desc)
        f.write(arr.tobytes())


def _varint(val):
    if val < 0:
        val += 1 << 64
    out = bytearray()
    while True:
        b = val & 0x7F
        val >>= 7
        if val:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def main():
    tmp = tempfile.mkdtemp()
    fp = compile_proto(tmp)
    d = fp.ProgramDesc()
    b = d.blocks.add()
    b.idx, b.parent_idx = 0, -1

    def lod_var(name, dims, persistable=False, dtype=fp.VarType.FP32):
        v = b.vars.add()
        v.name = name
        v.type.type = fp.VarType.LOD_TENSOR
        v.type.lod_tensor.tensor.data_type = dtype
        v.type.lod_tensor.tensor.dims.extend(dims)
        v.persistable = persistable
        return v

    feed = b.vars.add()
    feed.name = "feed"
    feed.type.type = fp.VarType.FEED_MINIBATCH
    feed.persistable = True
    fetch = b.vars.add()
    fetch.name = "fetch"
    fetch.type.type = fp.VarType.FETCH_LIST
    fetch.persistable = True
    lod_var("img", [-1, 784])
    lod_var("fc_0.w_0", [784, 10], persistable=True)
    lod_var("fc_0.b_0", [10], persistable=True)
    lod_var("fc_0.tmp_0", [-1, 10])
    lod_var("fc_0.tmp_1", [-1, 10])
    lod_var("prob", [-1, 10])

    def op(type_, inputs, outputs, attrs=()):
        o = b.ops.add()
        o.type = type_
        for p, args in inputs:
            iv = o.inputs.add()
            iv.parameter = p
            iv.arguments.extend(args)
        for p, args in outputs:
            ov = o.outputs.add()
            ov.parameter = p
            ov.arguments.extend(args)
        for name, atype, val in attrs:
            a = o.attrs.add()
            a.name, a.type = name, atype
            if atype == fp.INT:
                a.i = val
            elif atype == fp.FLOAT:
                a.f = val
        return o

    op("feed", [("X", ["feed"])], [("Out", ["img"])],
       [("col", fp.INT, 0)])
    op("mul", [("X", ["img"]), ("Y", ["fc_0.w_0"])],
       [("Out", ["fc_0.tmp_0"])],
       [("x_num_col_dims", fp.INT, 1), ("y_num_col_dims", fp.INT, 1)])
    op("elementwise_add", [("X", ["fc_0.tmp_0"]), ("Y", ["fc_0.b_0"])],
       [("Out", ["fc_0.tmp_1"])], [("axis", fp.INT, 1)])
    op("softmax", [("X", ["fc_0.tmp_1"])], [("Out", ["prob"])])
    op("fetch", [("X", ["prob"])], [("Out", ["fetch"])],
       [("col", fp.INT, 0)])
    d.version.version = 0

    os.makedirs(OUT, exist_ok=True)
    with open(os.path.join(OUT, "__model__"), "wb") as f:
        f.write(d.SerializeToString())
    rng = np.random.RandomState(7)
    write_ref_lod_tensor(os.path.join(OUT, "fc_0.w_0"),
                         rng.randn(784, 10).astype("float32") * 0.05)
    write_ref_lod_tensor(os.path.join(OUT, "fc_0.b_0"),
                         rng.randn(10).astype("float32") * 0.05)
    print("fixture written to", OUT)


if __name__ == "__main__":
    main()
