#!/usr/bin/env python
"""proglint — static verifier CLI over the benchmark model programs.

Builds every model in benchmark/fluid/models/ (forward + backward +
optimizer, exactly like fluid_benchmark.py) and runs the
paddle_tpu.analysis pass pipeline over the resulting Programs. Exit
status is non-zero when any error-severity diagnostic fires (or any
warning with --strict), so this doubles as a CI gate.

Examples:
  python tools/proglint.py                      # all models
  python tools/proglint.py mnist resnet         # a subset
  python tools/proglint.py --dot /tmp/lint      # annotated .dot graphs
  python tools/proglint.py --json               # machine-readable
"""
import argparse
import json
import os
import sys
import types

# static analysis never needs an accelerator; also keeps the CLI usable
# on machines whose TPU is held by a training job
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "benchmark", "fluid"))

ALL_MODELS = ["machine_translation", "resnet", "vgg", "mnist",
              "stacked_dynamic_lstm", "se_resnext"]


def model_args(batch_size=32):
    """The slice of benchmark/fluid/args.py defaults the model builders
    read (vision models look at data_set; the rest take none)."""
    return types.SimpleNamespace(
        batch_size=batch_size, data_set="cifar10", data_format="NCHW",
        learning_rate=0.001, infer_only=False, use_bf16=False)


def build_model_programs(name, args=None):
    """(main_program, startup_program, loss_var) for one benchmark
    model, built the same way fluid_benchmark.py builds it."""
    import paddle_tpu as fluid
    args = args or model_args()
    model_mod = __import__(f"models.{name}", fromlist=["get_model"])
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        with fluid.unique_name.guard():
            loss, _ = model_mod.get_model(args)
            opt = fluid.optimizer.Adam(args.learning_rate) \
                if name == "machine_translation" \
                else fluid.optimizer.Momentum(args.learning_rate, 0.9)
            if not args.infer_only:
                opt.minimize(loss)
    return main_p, startup_p, loss


def lint_model(name, dot_dir=None):
    """Verify one model; returns (diagnostics, op_count)."""
    main_p, startup_p, loss = build_model_programs(name)
    diags = main_p.verify(fetch_list=[loss])
    # the startup program initializes state: its fetch set is empty by
    # design, so skip dead-code there (every op writes persistables)
    diags += startup_p.verify()
    if dot_dir:
        from paddle_tpu.debugger import draw_block_graphviz
        os.makedirs(dot_dir, exist_ok=True)
        draw_block_graphviz(main_p.global_block(), diagnostics=diags,
                            path=os.path.join(dot_dir, f"{name}.dot"))
    n_ops = sum(len(b.ops) for b in main_p.blocks)
    return diags, n_ops


def main(argv=None):
    from paddle_tpu.analysis import format_diagnostics, pass_names

    p = argparse.ArgumentParser(
        description="static program verifier over the benchmark models")
    p.add_argument("models", nargs="*", default=None,
                   help=f"models to lint (default: all of {ALL_MODELS})")
    p.add_argument("--dot", metavar="DIR", default=None,
                   help="write annotated graphviz .dot per model")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable diagnostics on stdout")
    p.add_argument("--strict", action="store_true",
                   help="warnings also fail the exit status")
    p.add_argument("--quiet", action="store_true",
                   help="suppress info-severity diagnostics")
    p.add_argument("--list-passes", action="store_true",
                   help="print registered pass names and exit")
    args = p.parse_args(argv)

    if args.list_passes:
        print("\n".join(pass_names()))
        return 0

    models = args.models or ALL_MODELS
    bad = [m for m in models if m not in ALL_MODELS]
    if bad:
        p.error(f"unknown model(s) {bad}; choose from {ALL_MODELS}")

    failed = False
    report = {}
    for name in models:
        diags, n_ops = lint_model(name, dot_dir=args.dot)
        if args.quiet:
            diags = [d for d in diags if d.severity != "info"]
        report[name] = [d.to_dict() for d in diags]
        n_err = sum(d.severity == "error" for d in diags)
        n_warn = sum(d.severity == "warning" for d in diags)
        if n_err or (args.strict and n_warn):
            failed = True
        if not args.as_json:
            status = "FAIL" if n_err else ("warn" if n_warn else "ok")
            print(f"{name:<24} {n_ops:>4} ops  {n_err} error(s), "
                  f"{n_warn} warning(s)  [{status}]")
            if diags:
                print("  " + format_diagnostics(diags).replace(
                    "\n", "\n  "))
    if args.as_json:
        print(json.dumps(report, indent=2))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
