#!/usr/bin/env python
"""tputrace — per-request trace exemplars: list, inspect, export.

The serving tier (PADDLE_TPU_REQTRACE=1) captures full event traces
for *tail* requests only — latency above the live p99, deadline miss,
brownout shed, budget denial, hedge fired, crash resubmit, chaos fault
— up to a fixed exemplar budget. `telemetry.flush` writes them to
`$PADDLE_TPU_TELEMETRY_DIR/traces.json`; a live server exposes them at
`GET /v1/traces`. This CLI reads either.

  list        summary table of the stored traces (one row per request:
              status, latency, trigger mix, event count)
  show ID     one exemplar as an indented event tree (frontend events
              plus per-replica legs); `--chrome OUT` also writes
              Chrome trace-event JSON (chrome://tracing, Perfetto) —
              one pid per replica, pid 0 is the frontend
  --selftest  CI gate (pattern of tools/tpudoctor.py --selftest): a
              deterministic chaos run — replica_slow hedging, a
              worker_crash resubmit, a forced brownout shed — must
              capture exemplars for exactly the triggered requests;
              the hedged exemplar must show the full cross-replica
              causal chain (hedge launch, loser cancel, winner, legs
              on two distinct replica pids with consistent parent
              links); with tracing off the serve path must not even
              import telemetry.reqtrace and must return byte-identical
              tokens. One JSON verdict line with --json; exit 2 on
              any problem.

Examples:
  python tools/tputrace.py list --path telemetry/traces.json
  python tools/tputrace.py list --url http://localhost:8000
  python tools/tputrace.py show 4f2a... --path telemetry/traces.json \\
      --chrome /tmp/req.trace.json
  python tools/tputrace.py --selftest --json
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


# ------------------------------------------------------------- sources
def _fetch(url):
    import urllib.request
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read().decode())


def _load_index(args):
    """The trace index: a traces.json artifact (--path) or a live
    server's /v1/traces (--url). Both carry {seen, kept, triggers,
    traces: [...]}; artifact rows keep their events inline."""
    if args.url:
        base = args.url.rstrip("/")
        if not base.endswith("/v1/traces"):
            base += "/v1/traces"
        return _fetch(base)
    if args.path:
        with open(args.path) as f:
            return json.load(f)
    raise SystemExit("tputrace: need --path FILE or --url URL")


def _row_events(row):
    n = row.get("n_events")
    if n is None:
        n = len(row.get("events") or [])
    return n


# ----------------------------------------------------------------- list
def cmd_list(args):
    payload = _load_index(args)
    trig = payload.get("triggers") or {}
    mix = " ".join(f"{k}={v}" for k, v in sorted(trig.items()))
    print(f"traces: kept {payload.get('kept', 0)}/"
          f"{payload.get('seen', 0)} seen, "
          f"{len(payload.get('traces') or [])} stored "
          f"(budget {payload.get('budget', '?')})"
          + (f"  [{mix}]" if mix else ""))
    rows = payload.get("traces") or []
    if not rows:
        return 0
    print(f"  {'trace_id':<20} {'status':<10} {'latency_ms':>10} "
          f"{'events':>7}  triggers")
    for row in rows:
        n = _row_events(row)
        print(f"  {row['trace_id']:<20} {row['status']:<10} "
              f"{row['latency_ms']:>10.2f} "
              f"{n if n else '-':>7}  "
              f"{','.join(row['triggers']) or '-'}")
    return 0


# ----------------------------------------------------------------- show
def _render_events(row):
    """Indented event tree for one exemplar row: children under their
    parent span, frontend vs replica called out per line."""
    events = row.get("events") or []
    by_parent = {}
    ids = {e["span_id"] for e in events}
    for e in events:
        p = e.get("parent_id")
        by_parent.setdefault(p if p in ids else None, []).append(e)
    t0 = row.get("t0_us") or (events[0]["ts_us"] if events else 0)
    lines, walked = [], set()

    def walk(parent, depth):
        # the root's B and E phases share one span id: recurse into a
        # span's children once, not once per phase row
        if parent in walked:
            return
        walked.add(parent)
        for e in by_parent.get(parent, ()):
            where = ("frontend" if e.get("replica") is None
                     else f"replica {e['replica']}")
            dur = (f" dur={e['dur_us'] / 1000.0:.2f}ms"
                   if e.get("ph") == "X" else "")
            extra = {k: v for k, v in (e.get("args") or {}).items()}
            lines.append(
                f"  {'  ' * depth}+{(e['ts_us'] - t0) / 1000.0:8.2f}ms "
                f"{e['name']:<24} [{where}]{dur}"
                + (f"  {json.dumps(extra, default=str)}" if extra
                   else ""))
            walk(e["span_id"], depth + 1)

    walk(None, 0)
    return lines


def cmd_show(args):
    if args.url:
        base = args.url.rstrip("/")
        if not base.endswith("/v1/traces"):
            base += "/v1/traces"
        chrome = _fetch(f"{base}/{args.trace_id}")
        meta = chrome.get("metadata") or {}
        print(f"trace {args.trace_id}: status={meta.get('status')} "
              f"latency={meta.get('latency_ms', 0):.2f}ms "
              f"triggers={','.join(meta.get('triggers') or []) or '-'}")
        for e in chrome.get("traceEvents", []):
            if e.get("ph") == "M":
                continue
            print(f"  pid {e['pid']} {e['ts']:>12} {e['name']}")
        if args.chrome:
            with open(args.chrome, "w") as f:
                json.dump(chrome, f, indent=2)
            print(f"chrome trace written to {args.chrome}")
        return 0
    payload = _load_index(args)
    row = next((r for r in payload.get("traces") or []
                if r["trace_id"] == args.trace_id), None)
    if row is None:
        print(f"tputrace: trace {args.trace_id!r} not found",
              file=sys.stderr)
        return 1
    print(f"trace {row['trace_id']}: status={row['status']} "
          f"latency={row['latency_ms']:.2f}ms "
          f"triggers={','.join(row['triggers']) or '-'} "
          f"events={_row_events(row)}")
    if row.get("args"):
        print(f"  args: {json.dumps(row['args'], default=str)}")
    for line in _render_events(row):
        print(line)
    if not row.get("events"):
        print("  (summary row only — this trace fired no capture "
              "trigger, its events were not materialised)")
    if args.chrome:
        from paddle_tpu.telemetry import reqtrace as rt
        with open(args.chrome, "w") as f:
            json.dump(rt.chrome_trace_from(row), f, indent=2)
        print(f"chrome trace written to {args.chrome}")
    return 0


# ------------------------------------------------------------- selftest
def _decode_stack(seed=7, maxlen=12, vocab=64, d_model=32, n_layer=2):
    """Tiny seeded transformer (the tpuserve selftest stack): infer
    program + executor + params dict for the decode engines."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.core import framework as fw
    from paddle_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        src_vocab=vocab, trg_vocab=vocab, max_len=maxlen,
        d_model=d_model, d_inner=2 * d_model, n_head=4,
        n_layer=n_layer, dropout=0.0, label_smooth_eps=0.0)
    infer, start = fw.Program(), fw.Program()
    with pt.program_guard(infer, start):
        with pt.unique_name.guard():
            _feeds, logits = tfm.build_infer_program(cfg, maxlen=maxlen)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(start)
    rng = np.random.RandomState(seed)
    scope = pt.global_scope()
    params = {}
    for v in infer.persistable_vars():
        a = np.asarray(scope.get(v.name))
        if v.name.startswith("layer_norm") and v.name.endswith(".w_0"):
            nv = 1.0 + 0.2 * rng.randn(*a.shape)
        elif v.name.endswith(".b_0"):
            nv = 0.1 * rng.randn(*a.shape)
        else:
            nv = 0.35 * rng.randn(*a.shape)
        nv = nv.astype(a.dtype)
        scope.set(v.name, nv)
        params[v.name] = nv
    return cfg, exe, infer, logits, params


def _selftest_problems(problems):
    """Runs the deterministic chaos scenario; appends failures to
    `problems`, returns the info dict for the verdict line."""
    import numpy as np
    from paddle_tpu import telemetry as tm
    from paddle_tpu.models import transformer as tfm
    from paddle_tpu.resilience import chaos
    from paddle_tpu.serving.batcher import BrownoutShed
    from paddle_tpu.serving.decode import (DecodeConfig,
                                           DecodeEngineConfig)
    from paddle_tpu.serving.decode.qos import QosPolicy
    from paddle_tpu.serving.farm import FarmConfig, ReplicaGroup
    from paddle_tpu.serving.guard import GuardConfig

    tm.enable()
    tm.reset()
    tm.reqtrace_disable()
    chaos.reset()

    maxlen = 12
    cfg, exe, infer, logits, params = _decode_stack(maxlen=maxlen)

    def ref(src, n, max_new):
        row = np.zeros((1, maxlen), np.int64)
        row[0, :n] = src
        ids = tfm.greedy_decode(exe, infer, logits, row,
                                np.array([n], "int64"), bos=0,
                                fetch_argmax=True)
        return ids[0, 1:1 + max_new].astype(np.int64)

    def farm(name, guard, qos_factory=None, retries=2):
        return ReplicaGroup(cfg, params, FarmConfig(
            replicas=2,
            engine=DecodeEngineConfig(num_slots=2, max_len=maxlen,
                                      prefill_buckets=(1, 2)),
            decode=DecodeConfig(bos=0, max_queue_requests=64),
            retries=retries, guard=guard, qos_factory=qos_factory),
            name=name)

    # base group: hedging OFF (so phase C's crash actually resubmits),
    # generous retry budget, brownout thresholds never reached by load
    # (phase D forces entry through the miss EWMA), a two-weight QoS
    # so "shed the lowest class" has a victim
    base = farm("trace-base", GuardConfig(
        hedge=False, slow_factor=1e9, retry_rate=1000.0,
        retry_burst=1000, enter_streak=10**6, err_probation=2.0,
        queue_high=10**9),
        qos_factory=lambda: QosPolicy([("gold", 4.0), ("free", 1.0)]))
    base.start()
    src_a = np.arange(2, 9).astype("int64")
    want_a = ref(src_a, 7, 5)

    # ---- phase A: trace-off purity + byte-identical tokens ----------
    res_off = base.decode(src_a, src_len=7, max_new_tokens=5,
                          timeout=60, request_id="off-1")
    toks_off = np.asarray(res_off.tokens, np.int64)
    if "paddle_tpu.telemetry.reqtrace" in sys.modules:
        problems.append(
            "trace-off serve path imported telemetry.reqtrace — the "
            "PADDLE_TPU_REQTRACE-unset purity contract is broken")
    if not np.array_equal(toks_off, want_a):
        problems.append("trace-off tokens diverged from greedy ref")

    tm.reqtrace_enable()
    res_on = base.decode(src_a, src_len=7, max_new_tokens=5,
                         timeout=60, request_id="on-1")
    toks_on = np.asarray(res_on.tokens, np.int64)
    if toks_on.tobytes() != toks_off.tobytes():
        problems.append(
            "tracing changed the answer: tokens are not "
            "byte-identical with PADDLE_TPU_REQTRACE on vs off")
    rt = tm.reqtrace
    if rt.trace_end("on-1"):
        problems.append("an untriggered request reported triggers")

    # ---- phase B: replica_slow -> hedge -> cross-replica chain ------
    hedged = farm("trace-hedge", GuardConfig(
        hedge_fixed_delay_s=0.0, hedge_fraction=1.0, hedge_burst=1e9,
        retry_rate=1000.0, retry_burst=1000, slow_factor=1e9,
        enter_streak=10**6, err_probation=2.0, queue_high=10**9))
    hedged.start()
    src_b = np.arange(3, 10).astype("int64")
    want_b = ref(src_b, 7, 6)
    chaos.configure("replica_slow:ms=60,replica=0")
    try:
        res_h = hedged.decode(src_b, src_len=7, max_new_tokens=6,
                              timeout=60, request_id="hedge-1")
    finally:
        chaos.reset()
    if not np.array_equal(np.asarray(res_h.tokens, np.int64), want_b):
        problems.append("hedged request tokens diverged from ref")
    trig_h = rt.trace_end("hedge-1")
    hedged.stop(drain=True, timeout=30.0)
    if "hedge" not in trig_h:
        problems.append(f"hedge trigger missing: {trig_h}")
    row_h = rt.get("hedge-1")
    hedge_pids = []
    if row_h is None or not row_h["events"]:
        problems.append("hedged exemplar was not captured")
    else:
        evs = row_h["events"]
        names = [e["name"] for e in evs]
        for need in ("request", "leg.primary", "leg.hedge",
                     "farm.hedge.launch", "farm.hedge.cancel",
                     "farm.win", "decode.enqueue", "decode.admit",
                     "decode.step", "decode.retire", "engine.prefill"):
            if need not in names:
                problems.append(
                    f"hedged exemplar missing {need!r} event")
        legs = {e["replica"]: e["span_id"] for e in evs
                if e["name"].startswith("leg.")}
        if len(legs) != 2:
            problems.append(
                f"hedged legs landed on {sorted(legs)} — expected two "
                f"distinct replicas")
        root = row_h["root_id"]
        for e in evs:
            if e["name"].startswith("leg.") \
                    and e["parent_id"] != root:
                problems.append(
                    f"leg {e['name']} parent {e['parent_id']} != "
                    f"request root {root}")
            if e["name"].startswith("decode.") \
                    and e["parent_id"] != legs.get(e["replica"]):
                problems.append(
                    f"{e['name']} on replica {e['replica']} parents "
                    f"to {e['parent_id']}, not its leg "
                    f"{legs.get(e['replica'])}")
        win = [e for e in evs if e["name"] == "farm.win"]
        lose = [e for e in evs if e["name"] == "farm.hedge.cancel"]
        if win and lose and win[0]["replica"] == lose[0]["replica"]:
            problems.append("hedge winner and cancelled loser report "
                            "the same replica")
        chrome = rt.chrome_trace("hedge-1")
        hedge_pids = sorted({e["pid"]
                             for e in chrome["traceEvents"]})
        if not {0, 1, 2}.issubset(hedge_pids):
            problems.append(
                f"chrome export pids {hedge_pids}: expected the "
                f"frontend pid 0 plus two replica pids")

    # ---- phase C: worker_crash -> resubmit under the same id --------
    src_c = np.arange(4, 11).astype("int64")
    want_c = ref(src_c, 7, 5)
    # at=2, not at=1: the first working iteration ADMITS the queued
    # request (chaos checks before admission); the second crashes with
    # the slot active, so the future dies and the farm must resubmit.
    # A crash at iteration 1 would hit a still-queued request, which
    # _crash_recover deliberately leaves queued for the respawned loop.
    chaos.configure("worker_crash:at=2")
    try:
        res_c = base.decode(src_c, src_len=7, max_new_tokens=5,
                            timeout=60, request_id="crash-1")
    finally:
        chaos.reset()
    if not np.array_equal(np.asarray(res_c.tokens, np.int64), want_c):
        problems.append("resubmitted request tokens diverged from ref")
    trig_c = rt.trace_end("crash-1")
    for need in ("chaos", "resubmit"):
        if need not in trig_c:
            problems.append(f"crash trigger {need!r} missing: {trig_c}")
    row_c = rt.get("crash-1")
    if row_c is None or not row_c["events"]:
        problems.append("crash exemplar was not captured")
    else:
        names = [e["name"] for e in row_c["events"]]
        for need in ("chaos.fault", "farm.resubmit", "leg.resubmit"):
            if need not in names:
                problems.append(
                    f"crash exemplar missing {need!r} event")
        reps = {e["replica"] for e in row_c["events"]
                if e["name"].startswith("leg.")}
        if len(reps) != 2:
            problems.append(
                f"crash legs landed on {sorted(reps)} — the resubmit "
                f"must move to a second replica under the SAME id")

    # ---- phase D: forced brownout -> lowest-QoS shed ----------------
    bo = base.guard.brownout
    while bo.miss_ewma < bo.miss_high:
        bo.on_deadline_miss()
    bo.observe(0)                        # enter on miss pressure
    if not bo.active:
        problems.append("brownout refused to enter on miss pressure")
    shed = None
    try:
        base.submit(src_a, src_len=7, max_new_tokens=5, tenant="free",
                    request_id="shed-1")
    except BrownoutShed as e:
        shed = e
    if shed is None:
        problems.append("brownout active but the lowest QoS class "
                        "was not shed")
    trig_s = rt.trace_end("shed-1", status="shed")
    if "shed" not in trig_s:
        problems.append(f"shed trigger missing: {trig_s}")
    row_s = rt.get("shed-1")
    if row_s is None or not row_s["events"]:
        problems.append("shed exemplar was not captured")
    elif "guard.brownout.shed" not in [e["name"]
                                       for e in row_s["events"]]:
        problems.append("shed exemplar missing guard.brownout.shed")
    base.stop(drain=True, timeout=30.0)

    # ---- exactness: exemplars for exactly the triggered requests ----
    snap = rt.snapshot()
    captured = {r["trace_id"] for r in snap["traces"] if r["captured"]}
    if captured != {"hedge-1", "crash-1", "shed-1"}:
        problems.append(
            f"captured set {sorted(captured)} != the triggered "
            f"requests ['crash-1', 'hedge-1', 'shed-1']")
    stored = {r["trace_id"] for r in snap["traces"]}
    if "on-1" not in stored:
        problems.append("untriggered trace lost its summary row")
    if "off-1" in stored:
        problems.append("a trace-off request leaked into the store")
    if snap["seen"] != 4:
        problems.append(f"seen {snap['seen']} != 4 completed traces")

    # fleet rollup gauges (publish() ran on every trace_end)
    msnap = tm.snapshot()
    if msnap.get("serving.trace.kept") != 3:
        problems.append(
            f"serving.trace.kept gauge "
            f"{msnap.get('serving.trace.kept')} != 3")

    # ---- artifact round-trip: dump -> file -> list/show/chrome ------
    import tempfile
    dump = rt.dump()
    with tempfile.NamedTemporaryFile("w", suffix=".traces.json",
                                     delete=False) as f:
        json.dump(dump, f, default=str)
        path = f.name
    try:
        with open(path) as f:
            back = json.load(f)
        row = next(r for r in back["traces"]
                   if r["trace_id"] == "hedge-1")
        if not _render_events(row):
            problems.append("show rendering of the reloaded exemplar "
                            "came back empty")
        from paddle_tpu.telemetry import reqtrace as _rt
        chrome2 = _rt.chrome_trace_from(row)
        if sorted({e["pid"] for e in chrome2["traceEvents"]}) \
                != hedge_pids:
            problems.append("chrome export changed across the "
                            "traces.json round-trip")
    finally:
        os.unlink(path)

    return {
        "seen": snap["seen"], "kept": snap["kept"],
        "stored": snap["stored"], "triggers": snap["triggers"],
        "hedge_pids": hedge_pids,
        "captured": sorted(captured),
    }


def run_selftest(args):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    problems = []
    info = _selftest_problems(problems)
    result = {"mode": "selftest", **info, "problems": problems,
              "ok": not problems}
    if args.json:
        print(json.dumps(result, default=str))
    else:
        print(f"tputrace selftest: {info['kept']}/{info['seen']} "
              f"exemplars kept ({', '.join(info['captured'])}), "
              f"trigger mix "
              + " ".join(f"{k}={v}"
                         for k, v in sorted(info["triggers"].items()))
              + f", hedged chrome pids {info['hedge_pids']}")
        for prob in problems:
            print(f"FAIL: {prob}", file=sys.stderr)
    return 2 if problems else 0


# ----------------------------------------------------------------- main
def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tputrace",
        description="per-request trace exemplars: list, show, export")
    ap.add_argument("--selftest", action="store_true",
                    help="run the CI gate")
    ap.add_argument("--json", action="store_true",
                    help="selftest: one JSON verdict line")
    sub = ap.add_subparsers(dest="cmd")
    lp = sub.add_parser("list", help="summary table of stored traces")
    lp.add_argument("--path", help="a traces.json artifact")
    lp.add_argument("--url", help="a live server (GET /v1/traces)")
    sp = sub.add_parser("show", help="one exemplar as an event tree")
    sp.add_argument("trace_id")
    sp.add_argument("--path", help="a traces.json artifact")
    sp.add_argument("--url", help="a live server (GET /v1/traces)")
    sp.add_argument("--chrome", metavar="OUT",
                    help="also write Chrome trace-event JSON")
    args = ap.parse_args(argv)
    if args.selftest:
        return run_selftest(args)
    if args.cmd == "list":
        return cmd_list(args)
    if args.cmd == "show":
        return cmd_show(args)
    ap.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
