"""On-chip MFU probe for the flagship transformer (VERDICT r3 #2).

Profiles the SURVEY §5 config (B=64, T=128, transformer-base, bf16)
with the device-side profiler and prints the xplane-derived op-family
breakdown, total device step time, and MFU — the measurement record
the round-3 verdict asked for. A/B knobs:

  python tools/mfu_probe.py                 # current defaults
  python tools/mfu_probe.py --no-fuse-tail  # disable stacked Adam tail
  python tools/mfu_probe.py --no-fused-qkv # unfused q/k/v matmuls
  python tools/mfu_probe.py --steps 20

Run on the real chip (axon relay). Ref: benchmark/fluid/
machine_translation.py is the reference's equivalent headline bench.
"""
import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--no-fuse-tail", action="store_true")
    ap.add_argument("--no-fused-qkv", action="store_true")
    ap.add_argument("--flash-bf16-softmax", action="store_true",
                    help="A/B the unvalidated bf16 flash softmax "
                         "escape (ops/pallas/flash_attention.py)")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--seqlen", type=int, default=128)
    args = ap.parse_args()

    import numpy as np
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core import trace as _trace
    from paddle_tpu.core.trace import build_step_fn
    from paddle_tpu.models import transformer as tfm
    from paddle_tpu.profiler import profile_step_fn
    import bench

    if args.no_fuse_tail:
        _trace.FUSE_OPTIMIZER_TAIL = False
    if args.flash_bf16_softmax:
        from paddle_tpu.ops.pallas import flash_attention as _fa
        _fa.set_softmax_dtype(jnp.bfloat16)

    B, T = args.batch, args.seqlen
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            cfg = tfm.TransformerConfig(
                src_vocab=8000, trg_vocab=8000, max_len=T,
                d_model=512, d_inner=2048, n_head=8, n_layer=6,
                dropout=0.1, fused_qkv=not args.no_fused_qkv)
            feeds, avg_cost, tok = tfm.build_program(cfg, maxlen=T)
            pt.optimizer.Adam(1e-3).minimize(avg_cost)
    pt.amp.cast_program_to_bf16(main_p)

    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        pt.amp.cast_params_to_bf16(main_p, scope)
        persist = {v.name: scope.get(v.name)
                   for v in main_p.persistable_vars()}

    rng = np.random.RandomState(0)
    src = rng.randint(3, cfg.src_vocab, (B, T)).astype("int32")
    trg = np.concatenate([np.zeros((B, 1), "int32"),
                          (src[:, :-1] + 1) % cfg.trg_vocab], axis=1)
    feed = {"src": jnp.asarray(src),
            "src_len": jnp.full(B, T, jnp.int32),
            "trg": jnp.asarray(trg),
            "trg_len": jnp.full(B, T, jnp.int32),
            "label": jnp.asarray((src + 1) % cfg.trg_vocab, jnp.int32)}
    key = jax.random.PRNGKey(0)

    step_fn = build_step_fn(main_p, [avg_cost.name], False, None)
    jfn, flops = bench._aot_compile(jax.jit(step_fn, donate_argnums=(0,)),
                                    (persist, feed, key))
    flops = flops or bench._transformer_analytic_flops(cfg, B, T)
    t0 = time.perf_counter()
    fetches, persist = jfn(persist, feed, key)
    loss0 = float(np.asarray(fetches[0]))
    print(f"first step (compile+run): {time.perf_counter()-t0:.1f}s "
          f"loss={loss0:.4f}", flush=True)

    state = {"p": persist}

    def one_step():
        fetches, state["p"] = jfn(state["p"], feed, key)
        return fetches

    dev_s, fams = profile_step_fn(one_step, steps=args.steps)
    peak = bench._peak_flops(jax.devices()[0])
    out = {
        "device_step_ms": round(dev_s * 1e3, 3),
        "device_mfu": round(flops / dev_s / peak, 4),
        "tokens_per_sec_device": round(B * T / dev_s, 1),
        "flops_per_step": flops,
        "fuse_tail": not args.no_fuse_tail,
        "loss": loss0,
        "op_families_ms": {k: round(v * 1e3, 3) for k, v in
                           sorted(fams.items(), key=lambda kv: -kv[1])},
    }
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
