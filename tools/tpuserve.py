#!/usr/bin/env python
"""tpuserve — serve a save_inference_model dir with dynamic batching.

The serving counterpart of tools/tpustat.py: loads a model directory
into `paddle_tpu.serving.ModelServer` (shape-bucketed dynamic batching,
admission control, warmup) and exposes the TF-Serving-shaped HTTP API:

  POST /v1/models/<name>:predict   {"inputs": {feed: tensor}, ...}
  GET  /healthz
  GET  /metrics                    (telemetry prometheus_text)

Modes:
  serve (default)  python tools/tpuserve.py MODEL_DIR --port 8500
  --bench          closed-loop load generator against the served model:
                   reports p50/p99 latency, throughput, compile count,
                   reject rate (one JSON line with --json)
  --selftest       CI gate in the tpustat --json style: builds an mnist
                   model, serves it, fires mixed-shape concurrent
                   requests over HTTP, and exits non-zero unless
                   compile_count <= bucket count, every response matches
                   unbatched InferenceEngine.run, and overload requests
                   are rejected within their deadline. Includes the
                   decode leg (below).
  --selftest-decode
                   just the tpudecode CI gate: continuous-batching
                   decode over a tiny transformer must be token-
                   identical to one-at-a-time greedy_decode under
                   staggered arrivals/mixed lengths, the executable
                   count must stay == prefill buckets + 1, and
                   overload must shed fast.
  --bench-decode   continuous-decode closed loop at ~10x overload vs
                   the PR 3 fixed-batch greedy_decode path on the SAME
                   model: goodput (useful tokens/s), p50/p99
                   time-to-first-token and per-token latency; writes
                   the BENCH_decode.json artifact.
  --selftest-farm  the tpufarm CI gate: a 2-replica group with
                   disaggregated prefill must be token-identical to
                   greedy_decode at the group compile pin, int8
                   block-quantized KV must match fp32 tokens within
                   the parity bound (max logit delta reported), one
                   replica crashed by chaos must not drop a single
                   request, and a rolling weight update must serve
                   both versions mid-update with zero drops.
  --bench-farm     replica-group serving across the farm axes (1 vs 2
                   replicas, fp32 vs int8 KV, pooled vs disaggregated
                   prefill): slots/device and goodput/device per
                   case; writes the BENCH_decode2.json artifact.
  --selftest-guard the tpuguard CI gate: hedged requests must cut p99
                   vs guard-off under replica_slow on 1 of 2 replicas
                   at greedy_decode token parity; a replica_flap'd
                   replica must be ejected, probed and re-admitted
                   with zero drops; request_poison must fail exactly
                   one request with the replica surviving probation;
                   brownout must shed only the lowest QoS class with
                   a Retry-After hint and recover, and the retry
                   budget must cap resubmissions with a typed error.
  --bench-guard    closed-loop p50/p99 with vs without hedging while
                   replica_slow throttles 1 of 2 replicas; writes
                   BENCH_guard.json and appends guard_* records to
                   the bench history spine (tpustat --slo).
  --selftest-scale the tpuscale CI gate: under a tpuchaos
                   traffic_spike the controller must ramp the group
                   1->N and back with zero dropped requests and ZERO
                   scale-up recompiles (shared build cache); an
                   overloaded guard must DEFER brownout while a free
                   device slice exists and shed exactly when the
                   planner reports the ceiling; an over-mem-cap grow
                   must be rejected by the meshlint pre-spawn gate.
                   Writes BENCH_autoscale.json + autoscale_* history
                   records.
  --bench-scale    static 1-replica vs SLO-autoscaled group under
                   the same traffic_spike script: goodput, peak
                   replicas, extra compiles; merges a bench section
                   into BENCH_autoscale.json.

Examples:
  python tools/tpuserve.py /models/mnist --name mnist --port 8500
  python tools/tpuserve.py /models/mnist --bench --duration 5 --json
  python tools/tpuserve.py --selftest --json
  python tools/tpuserve.py --selftest-decode --json
  python tools/tpuserve.py --bench-decode --duration 5 --json
  python tools/tpuserve.py --selftest-farm --json
  python tools/tpuserve.py --bench-farm --duration 5 --json
  python tools/tpuserve.py --selftest-guard --json
  python tools/tpuserve.py --bench-guard --duration 5 --json
  python tools/tpuserve.py --selftest-scale --json
  python tools/tpuserve.py --bench-scale --json
"""
import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _post_json(url, payload, timeout=30.0):
    """(status_code, decoded_body) — errors returned, not raised."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read())
        except Exception:
            body = {"error": str(e)}
        return e.code, body


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _build_server(args, dirname, name):
    from paddle_tpu.serving import (BatchConfig, HttpFrontend,
                                    ModelServer, ServerConfig)
    buckets = tuple(int(b) for b in args.buckets.split(",")) \
        if args.buckets else None
    cfg = ServerConfig(
        batch=BatchConfig(max_batch_size=args.max_batch_size,
                          max_wait_ms=args.max_wait_ms,
                          buckets=buckets,
                          max_queue_requests=args.max_queue),
        workers=args.workers,
        default_deadline_ms=args.deadline_ms)
    server = ModelServer(cfg)
    server.load(name, dirname)
    frontend = HttpFrontend(server, host=args.host, port=args.port)
    return server, frontend


def _mixed_feeds(engine, count, max_rows, seed=0):
    """`count` random feeds with batch sizes cycling over a mixed set
    (1..max_rows), dtypes/shapes from the engine's feed specs."""
    import numpy as np
    rng = np.random.RandomState(seed)
    sizes = [1, 2, 3, max(1, max_rows // 2), max_rows,
             max(1, max_rows - 1), max(1, max_rows // 4), 2]
    specs = engine.feed_specs()
    feeds = []
    for i in range(count):
        n = sizes[i % len(sizes)]
        feed = {}
        for fname, (shape, dt) in specs.items():
            full = (n,) + tuple(d if d != -1 else 1 for d in shape[1:])
            if np.dtype(dt).kind in "iu":
                feed[fname] = rng.randint(0, 10, full).astype(dt)
            else:
                feed[fname] = rng.rand(*full).astype(dt)
        feeds.append(feed)
    return feeds


# ----------------------------------------------------------------- bench
def run_bench(args):
    from paddle_tpu import telemetry
    telemetry.enable()
    name = args.name
    server, frontend = _build_server(args, args.model_dir, name)
    frontend.start()
    engine, _ = server.registry.get(name)
    warm_sigs = engine.signature_count()
    telemetry.reset()        # scope metrics to the measured loop

    feeds = _mixed_feeds(engine, 64, args.max_batch_size)
    url = f"{frontend.url}/v1/models/{name}:predict"
    stop_t = time.monotonic() + args.duration
    lock = threading.Lock()
    lat, rejects, errors, rows_done = [], [0], [0], [0]

    def worker(wid):
        i = wid
        while time.monotonic() < stop_t:
            feed = feeds[i % len(feeds)]
            i += args.concurrency
            payload = {"inputs": {k: v.tolist() for k, v in feed.items()}}
            if args.deadline_ms:
                payload["deadline_ms"] = args.deadline_ms
            t0 = time.perf_counter()
            status, body = _post_json(url, payload)
            dt = time.perf_counter() - t0
            rows = next(iter(feed.values())).shape[0]
            with lock:
                if status == 200:
                    lat.append(dt)
                    rows_done[0] += rows
                elif status in (429, 504):
                    rejects[0] += 1
                else:
                    errors[0] += 1

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(args.concurrency)]
    t_start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t_start

    frontend.stop()
    server.shutdown()
    lat.sort()
    snap = telemetry.snapshot()
    total = len(lat) + rejects[0] + errors[0]
    result = {
        "mode": "bench", "model": name,
        "duration_s": round(elapsed, 3),
        "concurrency": args.concurrency,
        "requests_ok": len(lat), "rejected": rejects[0],
        "errors": errors[0],
        "reject_rate": round(rejects[0] / total, 4) if total else 0.0,
        "throughput_rps": round(len(lat) / elapsed, 2),
        "throughput_rows_per_s": round(rows_done[0] / elapsed, 1),
        "latency_p50_ms": round(1e3 * _percentile(lat, 0.50), 3)
        if lat else None,
        "latency_p99_ms": round(1e3 * _percentile(lat, 0.99), 3)
        if lat else None,
        "compile_count_warmup": warm_sigs,
        "compile_count_steady": snap.get("inference.compile_count", 0),
        "signature_count": engine.signature_count(),
        "batches": snap.get("serving.batches", 0),
        "mean_rows_per_batch": round(
            rows_done[0] / snap["serving.batches"], 2)
        if snap.get("serving.batches") else None,
    }
    if args.as_json:
        print(json.dumps(result))
    else:
        for k, v in result.items():
            print(f"  {k:<24} {v}")
    return 1 if errors[0] else 0


# -------------------------------------------------------------- selftest
def _build_mnist_dir(tmpdir):
    """Train-free mnist MLP -> save_inference_model dir."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.models import mnist as zoo
    img = layers.data("pixel", shape=[784])
    predict = zoo.mlp(img)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(tmpdir, ["pixel"], [predict], exe)
    return tmpdir


class _StallEngine:
    """Duck-typed engine whose run() stalls — overload on demand."""

    def __init__(self, delay_s):
        self.delay_s = delay_s

    def feed_specs(self):
        return {"pixel": ((-1, 4), "float32")}

    def signature_count(self):
        return 0

    def run(self, feed, return_numpy=True):
        import numpy as np
        time.sleep(self.delay_s)
        return [np.zeros((next(iter(feed.values())).shape[0], 1),
                         dtype="float32")]


def run_selftest(args):
    import numpy as np
    from paddle_tpu import telemetry
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.serving import (BatchConfig, DynamicBatcher,
                                    DeadlineExceeded, HttpFrontend,
                                    ModelServer, RejectedError,
                                    ServerConfig)

    telemetry.enable()
    problems = []
    buckets = (4, 16)

    with tempfile.TemporaryDirectory() as tmpdir:
        model_dir = _build_mnist_dir(tmpdir)
        cfg = ServerConfig(
            batch=BatchConfig(max_batch_size=16, max_wait_ms=2.0,
                              buckets=buckets, max_queue_requests=256),
            workers=3)
        server = ModelServer(cfg)
        server.load("mnist", model_dir)
        engine, _ = server.registry.get("mnist")
        warm_sigs = engine.signature_count()
        if warm_sigs != len(buckets):
            problems.append(
                f"warmup compiled {warm_sigs} signatures, expected "
                f"exactly {len(buckets)} (one per bucket)")

        # mixed-shape concurrent traffic over HTTP vs unbatched reference
        ref = InferenceEngine.from_dir(model_dir)
        feeds = _mixed_feeds(engine, 48, 16, seed=7)
        expected = [ref.run(f)[0] for f in feeds]
        frontend = HttpFrontend(server, port=0).start()
        url = f"{frontend.url}/v1/models/mnist:predict"
        statuses = [None] * len(feeds)
        outputs = [None] * len(feeds)

        def fire(i):
            statuses[i], body = _post_json(url, {
                "inputs": {k: v.tolist() for k, v in feeds[i].items()},
                "deadline_ms": 30000})
            if statuses[i] == 200:
                outputs[i] = np.asarray(body["outputs"][0],
                                        dtype="float32")

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(len(feeds))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        mismatches = 0
        for i, exp in enumerate(expected):
            if statuses[i] != 200:
                problems.append(f"request {i} failed: HTTP {statuses[i]}")
            elif not np.allclose(outputs[i], exp, rtol=1e-4, atol=1e-6):
                mismatches += 1
        if mismatches:
            problems.append(f"{mismatches} responses differ from "
                            f"unbatched InferenceEngine.run")
        sigs = engine.signature_count()
        if sigs > len(buckets):
            problems.append(
                f"compile_count {sigs} exceeds bucket count "
                f"{len(buckets)} — shape bucketing is not containing "
                f"signature explosion")

        # healthz + metrics surfaces
        with urllib.request.urlopen(frontend.url + "/healthz") as r:
            if json.loads(r.read()).get("status") != "ok":
                problems.append("healthz not ok while serving")
        with urllib.request.urlopen(frontend.url + "/metrics") as r:
            metrics_text = r.read().decode()
        for needle in ("serving_batches", "inference_signature_count"):
            if needle not in metrics_text:
                problems.append(f"/metrics missing {needle}")

        # overload over HTTP: one stalled worker, bounded queue, short
        # deadlines — rejections must come back fast, not queue forever
        slow = ModelServer(ServerConfig(
            batch=BatchConfig(max_batch_size=4, max_wait_ms=0.0,
                              buckets=(4,), max_queue_requests=2),
            workers=1, warmup=False))
        slow.register("slow", _StallEngine(0.3))
        sfront = HttpFrontend(slow, port=0).start()
        surl = f"{sfront.url}/v1/models/slow:predict"
        deadline_ms = 200.0
        reject_lat, ok_n, late = [], [0], [0]

        def flood(i):
            t0 = time.perf_counter()
            status, _body = _post_json(surl, {
                "inputs": {"pixel": [[0.0] * 4]},
                "deadline_ms": deadline_ms})
            dt = time.perf_counter() - t0
            if status == 200:
                ok_n[0] += 1
            else:
                reject_lat.append(dt)
                # client-observed: deadline + generous slack for 24
                # client threads contending on the GIL; the hard bound
                # on *server-side* queueing is the flood-duration check
                if dt > deadline_ms / 1e3 + 2.0:
                    late[0] += 1

        flooders = [threading.Thread(target=flood, args=(i,))
                    for i in range(24)]
        t_flood = time.monotonic()
        for t in flooders:
            t.start()
        for t in flooders:
            t.join()
        flood_s = time.monotonic() - t_flood
        if not reject_lat:
            problems.append("overload produced zero rejections "
                            "(queue grew unboundedly?)")
        if late[0]:
            problems.append(f"{late[0]} overload rejections took "
                            f"longer than deadline+2s")
        # had the 24 requests queued unboundedly behind the 0.3s/batch
        # stalled worker they would serialize to ~7s; load shedding
        # must finish the whole flood far sooner
        if flood_s > 5.0:
            problems.append(
                f"overload flood took {flood_s:.1f}s — requests piled "
                f"up behind the stalled worker instead of being shed")
        sfront.stop()
        slow.shutdown(drain=False, timeout=5.0)

        # admission control at the batcher level, deterministically:
        # no worker attached = a permanently stalled worker
        b = DynamicBatcher(BatchConfig(max_batch_size=4, buckets=(4,),
                                       max_queue_requests=2))
        f1 = b.submit({"x": np.zeros((1, 2))}, deadline_ms=100)
        b.submit({"x": np.zeros((1, 2))})
        t0 = time.perf_counter()
        try:
            b.submit({"x": np.zeros((1, 2))})
            problems.append("queue-full submit was admitted")
        except RejectedError:
            if time.perf_counter() - t0 > 0.1:
                problems.append("queue-full rejection was not fast")
        t0 = time.perf_counter()
        try:
            f1.result()
            problems.append("stalled request returned a result")
        except DeadlineExceeded:
            if time.perf_counter() - t0 > 1.0:
                problems.append("deadline enforcement took > 1s on a "
                                "stalled worker")

        snap = telemetry.snapshot()
        frontend.stop()
        server.shutdown()

    # decode leg: continuous batching must match one-at-a-time
    # greedy_decode exactly, with a pinned executable count
    decode_info = _decode_selftest_problems(problems)

    result = {
        "mode": "selftest",
        "decode": decode_info,
        "buckets": list(buckets),
        "warmup_signatures": warm_sigs,
        "signatures_after_traffic": sigs,
        "requests": len(feeds),
        "mismatches": mismatches,
        "overload": {"sent": 24, "ok": ok_n[0],
                     "rejected": len(reject_lat),
                     "duration_s": round(flood_s, 3),
                     "max_reject_latency_s":
                     round(max(reject_lat), 3) if reject_lat else None},
        "metrics": {k: v for k, v in sorted(snap.items())
                    if not isinstance(v, dict)},
        "problems": problems,
        "ok": not problems,
    }
    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        print(f"tpuserve selftest: warmup {warm_sigs} sigs for "
              f"{len(buckets)} buckets; {len(feeds)} mixed-shape "
              f"requests, {mismatches} mismatches; overload "
              f"{len(reject_lat)}/24 rejected "
              f"(max {result['overload']['max_reject_latency_s']}s)")
        for prob in problems:
            print(f"FAIL: {prob}", file=sys.stderr)
    return 2 if problems else 0


# -------------------------------------------------------------- tpudecode
def _decode_stack(seed=7, maxlen=16, vocab=64, d_model=32, n_layer=2):
    """Tiny transformer for the decode selftest/bench: infer program +
    executor with SEEDED random parameters (drawn wide enough that
    argmax tokens vary across rows/steps — a fresh default init is
    degenerate) and the same params as a plain dict for the decode
    engine. Returns (cfg, exe, infer_program, logits_var, params)."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.core import framework as fw
    from paddle_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        src_vocab=vocab, trg_vocab=vocab, max_len=maxlen,
        d_model=d_model, d_inner=2 * d_model, n_head=4,
        n_layer=n_layer, dropout=0.0, label_smooth_eps=0.0)
    infer, start = fw.Program(), fw.Program()
    with pt.program_guard(infer, start):
        with pt.unique_name.guard():
            _feeds, logits = tfm.build_infer_program(cfg, maxlen=maxlen)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(start)
    rng = np.random.RandomState(seed)
    scope = pt.global_scope()
    params = {}
    for v in infer.persistable_vars():
        a = np.asarray(scope.get(v.name))
        if v.name.startswith("layer_norm") and v.name.endswith(".w_0"):
            nv = 1.0 + 0.2 * rng.randn(*a.shape)
        elif v.name.endswith(".b_0"):
            nv = 0.1 * rng.randn(*a.shape)
        else:
            nv = 0.35 * rng.randn(*a.shape)
        nv = nv.astype(a.dtype)
        scope.set(v.name, nv)
        params[v.name] = nv
    return cfg, exe, infer, logits, params


def _decode_requests(rng, count, maxlen, vocab, max_new_cap):
    """Seeded mixed-length request set [(src, src_len, max_new)...]."""
    reqs = []
    for _ in range(count):
        n = int(rng.randint(3, maxlen + 1))
        src = rng.randint(2, vocab - 2, (n,)).astype("int64")
        max_new = int(rng.randint(3, max_new_cap + 1))
        reqs.append((src, n, max_new))
    return reqs


def _decode_selftest_problems(problems):
    """The tpudecode CI leg; appends failures to `problems`, returns
    an info dict for the report."""
    import numpy as np
    from paddle_tpu.models.transformer import greedy_decode
    from paddle_tpu.serving import RejectedError, DeadlineExceeded
    from paddle_tpu.serving.decode import (ContinuousScheduler,
                                           DecodeConfig, DecodeEngine,
                                           DecodeEngineConfig)

    maxlen, slots, buckets = 16, 4, (1, 2, 4)
    cfg, exe, infer, logits, params = _decode_stack(maxlen=maxlen)
    engine = DecodeEngine(cfg, params, DecodeEngineConfig(
        num_slots=slots, max_len=maxlen, prefill_buckets=buckets))
    sched = ContinuousScheduler(engine, config=DecodeConfig(bos=0),
                                warmup=True)
    warm = engine.compile_count
    if warm != len(buckets) + 1:
        problems.append(
            f"decode warmup compiled {warm} executables, expected "
            f"{len(buckets)} prefill buckets + 1 step")

    # one-at-a-time greedy_decode reference (the legacy full-program
    # path, with the in-graph argmax fetch) for a mixed-length set
    rng = np.random.RandomState(11)
    reqs = _decode_requests(rng, 8, maxlen, cfg.trg_vocab,
                            engine.max_new_tokens)
    expected = []
    for src, n, max_new in reqs:
        row = np.zeros((1, maxlen), np.int64)
        row[0, :n] = src
        ids = greedy_decode(exe, infer, logits, row,
                            np.array([n], "int64"), bos=0,
                            fetch_argmax=True)
        expected.append(ids[0, 1:1 + max_new])

    # continuous, manually driven, STAGGERED arrivals: requests join
    # the running batch mid-flight, finished ones leave early
    futures = []
    arrivals = {0: [0, 1], 2: [2, 3, 4], 5: [5], 6: [6, 7]}
    it = 0
    while len(futures) < len(reqs) or not all(
            f.done() for f in futures):
        for i in arrivals.get(it, ()):
            src, n, max_new = reqs[i]
            futures.append(sched.submit(src, src_len=n,
                                        max_new_tokens=max_new))
        sched.run_iteration()
        it += 1
        if it > 600:
            problems.append("decode selftest did not converge in "
                            "600 iterations")
            break
    mismatches = 0
    for i, f in enumerate(futures):
        if not f.done():
            continue
        got = f.result(timeout=0).tokens
        if not np.array_equal(np.asarray(got, np.int64), expected[i]):
            mismatches += 1
    if mismatches:
        problems.append(
            f"{mismatches}/{len(reqs)} continuous-decode outputs "
            f"differ from one-at-a-time greedy_decode — iteration-"
            f"level batching changed the tokens")
    steady = engine.compile_count
    if steady != warm:
        problems.append(
            f"decode compiled {steady - warm} NEW executables under "
            f"traffic (compile count must stay prefill buckets + 1)")
    if sched.pool.free_count() != slots:
        problems.append("decode slots leaked after drain")

    # overload shed: no loop thread attached == permanently stalled
    # worker; the bounded queue + deadline must both fire fast
    shed = ContinuousScheduler(
        engine, config=DecodeConfig(max_queue_requests=2),
        warmup=False)
    f1 = shed.submit(np.arange(2, 6), deadline_ms=150)
    shed.submit(np.arange(2, 6))
    t0 = time.perf_counter()
    rejected_fast = deadline_fast = False
    try:
        shed.submit(np.arange(2, 6))
    except RejectedError:
        rejected_fast = time.perf_counter() - t0 < 0.1
    if not rejected_fast:
        problems.append("decode queue-full submit was not rejected "
                        "fast")
    t0 = time.perf_counter()
    try:
        f1.result()
        problems.append("stalled decode request returned a result")
    except DeadlineExceeded:
        deadline_fast = time.perf_counter() - t0 < 1.0
    if not deadline_fast:
        problems.append("decode deadline enforcement took > 1s on a "
                        "stalled scheduler")
    return {"warmup_executables": warm,
            "steady_executables": steady,
            "prefill_buckets": list(buckets),
            "requests": len(reqs),
            "mismatches": mismatches,
            "overload": {"rejected_fast": rejected_fast,
                         "deadline_fast": deadline_fast}}


def run_selftest_decode(args):
    from paddle_tpu import telemetry
    telemetry.enable()
    problems = []
    info = _decode_selftest_problems(problems)
    result = {"mode": "selftest-decode", **info,
              "problems": problems, "ok": not problems}
    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        print(f"tpuserve selftest-decode: {info['warmup_executables']} "
              f"executables for {len(info['prefill_buckets'])} prefill "
              f"buckets + 1 step; {info['requests']} staggered "
              f"requests, {info['mismatches']} mismatches")
        for prob in problems:
            print(f"FAIL: {prob}", file=sys.stderr)
    return 2 if problems else 0


def run_bench_decode(args):
    """Continuous decode vs the PR 3 fixed-batch path, same model,
    ~10x overload. Writes BENCH_decode.json next to the repo root."""
    import numpy as np
    from paddle_tpu import telemetry
    from paddle_tpu.models.transformer import greedy_decode
    from paddle_tpu.serving import RejectedError
    from paddle_tpu.serving.decode import (ContinuousScheduler,
                                           DecodeConfig, DecodeEngine,
                                           DecodeEngineConfig)
    telemetry.enable()

    maxlen, slots = args.decode_max_len, args.slots
    cfg, exe, infer, logits, params = _decode_stack(maxlen=maxlen)
    engine = DecodeEngine(cfg, params, DecodeEngineConfig(
        num_slots=slots, max_len=maxlen))
    sched = ContinuousScheduler(
        engine,
        config=DecodeConfig(max_queue_requests=4 * slots),
        warmup=True).start()

    rng = np.random.RandomState(23)
    reqs = _decode_requests(rng, 256, maxlen, cfg.trg_vocab,
                            engine.max_new_tokens)

    # ---- continuous tier: closed loop at ~10x the slot count --------
    stop_t = time.monotonic() + args.duration
    lock = threading.Lock()
    done_tokens, ttfts, per_tok, rejects = [0], [], [], [0]

    def client(wid):
        i = wid
        while time.monotonic() < stop_t:
            src, n, max_new = reqs[i % len(reqs)]
            i += 10 * slots
            try:
                r = sched.submit(src, src_len=n,
                                 max_new_tokens=max_new).result(
                    timeout=max(5.0, args.duration))
            except RejectedError:
                with lock:
                    rejects[0] += 1
                time.sleep(0.002)
                continue
            except TimeoutError:
                continue
            with lock:
                done_tokens[0] += len(r.tokens)
                if r.ttft_s is not None:
                    ttfts.append(r.ttft_s)
                if len(r.tokens) > 1:
                    per_tok.append(r.decode_s / len(r.tokens))

    clients = [threading.Thread(target=client, args=(w,), daemon=True)
               for w in range(10 * slots)]
    t0 = time.monotonic()
    for t in clients:
        t.start()
    for t in clients:
        t.join()
    cont_s = time.monotonic() - t0
    sched.stop(drain=False, timeout=10.0)
    ttfts.sort()
    per_tok.sort()
    continuous = {
        "duration_s": round(cont_s, 3),
        "goodput_tokens_per_s": round(done_tokens[0] / cont_s, 1),
        "completed_tokens": done_tokens[0],
        "rejected": rejects[0],
        "ttft_p50_ms": round(1e3 * _percentile(ttfts, 0.5), 2)
        if ttfts else None,
        "ttft_p99_ms": round(1e3 * _percentile(ttfts, 0.99), 2)
        if ttfts else None,
        "per_token_p50_ms": round(1e3 * _percentile(per_tok, 0.5), 2)
        if per_tok else None,
        "per_token_p99_ms": round(1e3 * _percentile(per_tok, 0.99), 2)
        if per_tok else None,
        "executables": engine.compile_count,
        "slots": slots,
    }

    # ---- PR 3 fixed-batch path: greedy_decode in rigid batches ------
    # (one [slots, T] executable re-running the whole prefix per
    # token; early finishers ride the batch to the end)
    stop_t = time.monotonic() + args.duration
    t0 = time.monotonic()
    useful = batches = 0
    i = 0
    while time.monotonic() < stop_t:
        group = [reqs[(i + j) % len(reqs)] for j in range(slots)]
        i += slots
        src = np.zeros((slots, maxlen), np.int64)
        src_len = np.zeros((slots,), np.int64)
        for j, (s, n, _mn) in enumerate(group):
            src[j, :n] = s
            src_len[j] = n
        greedy_decode(exe, infer, logits, src, src_len, bos=0,
                      fetch_argmax=True)
        useful += sum(mn for _s, _n, mn in group)
        batches += 1
    fixed_s = time.monotonic() - t0
    fixed = {
        "duration_s": round(fixed_s, 3),
        "goodput_tokens_per_s": round(useful / fixed_s, 1),
        "completed_tokens": useful,
        "batches": batches,
        "batch_rows": slots,
    }

    ratio = None
    if fixed["goodput_tokens_per_s"]:
        ratio = round(continuous["goodput_tokens_per_s"]
                      / fixed["goodput_tokens_per_s"], 2)
    result = {"mode": "bench-decode", "model": "transformer-tiny",
              "maxlen": maxlen, "overload_clients": 10 * slots,
              "continuous": continuous, "fixed_batch": fixed,
              "goodput_ratio": ratio}
    out_path = os.path.join(_REPO, "BENCH_decode.json")
    try:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
    except OSError:
        pass
    if args.as_json:
        print(json.dumps(result))
    else:
        print(f"  continuous goodput  "
              f"{continuous['goodput_tokens_per_s']} tok/s "
              f"(ttft p50 {continuous['ttft_p50_ms']} ms)")
        print(f"  fixed-batch goodput {fixed['goodput_tokens_per_s']} "
              f"tok/s")
        print(f"  ratio               {ratio}x")
    return 0


# ------------------------------------------------------------------- farm
def _farm_group(cfg, params, replicas, slots, maxlen, buckets,
                prefill_devices=0, kv_quant=None, name="farm",
                max_queue=64, retries=1, guard=None, qos_factory=None):
    from paddle_tpu.serving.decode import (DecodeConfig,
                                           DecodeEngineConfig)
    from paddle_tpu.serving.farm import FarmConfig, ReplicaGroup
    return ReplicaGroup(cfg, params, FarmConfig(
        replicas=replicas, prefill_devices=prefill_devices,
        engine=DecodeEngineConfig(num_slots=slots, max_len=maxlen,
                                  prefill_buckets=buckets,
                                  kv_quant=kv_quant),
        decode=DecodeConfig(bos=0, max_queue_requests=max_queue),
        retries=retries, guard=guard, qos_factory=qos_factory),
        name=name)


def _pump_group(group, futures, problems, label, budget=800):
    """Drive a non-started group until every future resolves; crashed
    requests are resubmitted by GroupFuture on the result() poll."""
    from paddle_tpu.resilience.chaos import ChaosFault
    results = {}
    pending = dict(enumerate(futures))
    left = budget
    while pending and left:
        left -= 1
        for i, f in list(pending.items()):
            if not f.done():
                continue
            try:
                results[i] = f.result(timeout=0)
                del pending[i]
            except TimeoutError:
                pass            # resubmitted to another replica
        if pending:
            try:
                group.run_iteration()
            except ChaosFault as e:
                # manual drive has no supervisor thread: reclaim the
                # crashed replica's slots by hand, like _loop_guarded
                rep = group.replicas[0]
                rep.scheduler._crash_recover(e)
                rep.scheduler.restarts += 1
    if pending:
        problems.append(f"farm {label}: {len(pending)} requests never "
                        f"completed in {budget} iterations")
    return results


def _farm_parity_leg(problems, cfg, exe, infer, logits, params,
                     maxlen, buckets):
    """Leg 1: a 2-replica group with disaggregated prefill must be
    token-identical to one-at-a-time greedy_decode, spread load across
    both replicas, and stay at the group-level compile pin."""
    import numpy as np
    from paddle_tpu import telemetry
    from paddle_tpu.models.transformer import greedy_decode

    slots = 4
    group = _farm_group(cfg, params, replicas=2, slots=slots,
                        maxlen=maxlen, buckets=buckets,
                        prefill_devices=1, name="selftest")
    warm = group.compile_count
    if warm != len(buckets) + 1:
        problems.append(
            f"farm warmup built {warm} executables for 2 replicas, "
            f"expected {len(buckets)} shared prefill buckets + 1 "
            f"shared step")

    rng = np.random.RandomState(11)
    reqs = _decode_requests(rng, 8, maxlen, cfg.trg_vocab,
                            group.replicas[0].engine.max_new_tokens)
    expected = []
    for src, n, max_new in reqs:
        row = np.zeros((1, maxlen), np.int64)
        row[0, :n] = src
        ids = greedy_decode(exe, infer, logits, row,
                            np.array([n], "int64"), bos=0,
                            fetch_argmax=True)
        expected.append(ids[0, 1:1 + max_new])
    futures = [group.submit(src, src_len=n, max_new_tokens=mn)
               for src, n, mn in reqs]
    results = _pump_group(group, futures, problems, "parity")
    mismatches = sum(
        1 for i, r in results.items()
        if not np.array_equal(np.asarray(r.tokens, np.int64),
                              expected[i]))
    if mismatches:
        problems.append(
            f"{mismatches}/{len(reqs)} farm-decoded outputs differ "
            f"from greedy_decode — routing or the prefill handoff "
            f"changed the tokens")
    spread = [r.scheduler.tokens_generated for r in group.replicas]
    if min(spread) == 0:
        problems.append(f"router sent every request to one replica "
                        f"(tokens per replica: {spread})")
    if group.compile_count != warm:
        problems.append(
            f"farm compiled {group.compile_count - warm} NEW "
            f"executables under traffic")
    for r in group.replicas:
        r.scheduler.pool.check()
        if r.scheduler.pool.free_count() != slots:
            problems.append(f"replica {r.index} leaked slots")
    handoffs = telemetry.counter("serving.decode.handoffs").value
    if not handoffs:
        problems.append("disaggregated prefill never handed KV "
                        "device-to-device")
    return {"compile_count": warm, "requests": len(reqs),
            "mismatches": mismatches, "tokens_per_replica": spread,
            "prefill_devices": [str(d)
                                for d in group.prefill_devices],
            "handoffs": int(handoffs)}


def _farm_int8_leg(problems, cfg, params, maxlen):
    """Leg 2: int8 block-quantized KV vs the fp32 cache on the SAME
    weights, teacher-forced so per-step logits stay comparable."""
    import jax
    import numpy as np
    from paddle_tpu.models.transformer import IncrementalDecoder

    devs = jax.devices()
    dec_f = IncrementalDecoder(cfg, params, num_slots=2,
                               max_len=maxlen, return_logits=True,
                               device=devs[0])
    dec_q = IncrementalDecoder(cfg, params, num_slots=2,
                               max_len=maxlen, return_logits=True,
                               kv_quant="int8",
                               device=devs[1 % len(devs)])
    rng = np.random.RandomState(3)
    mismatch = total = 0
    max_delta = 0.0
    for n0, n1 in ((3, 5), (7, 10), (12, maxlen - 1)):
        src = np.zeros((2, dec_f.src_max_len), np.int64)
        src[0, :n0] = rng.randint(2, cfg.src_vocab - 2, n0)
        src[1, :n1] = rng.randint(2, cfg.src_vocab - 2, n1)
        sl = np.array([n0, n1], "int64")
        st_f = dec_f.write_slots(dec_f.init_state(),
                                 dec_f.prefill(src, sl), [0, 1])
        st_q = dec_q.write_slots(dec_q.init_state(),
                                 dec_q.prefill(src, sl), [0, 1])
        ids = np.zeros(2, np.int64)
        pos = np.zeros(2, np.int64)
        for _ in range(8):
            nf = dec_f.step(st_f, ids, pos)
            lf = dec_f.last_logits[:2].copy()
            nq = dec_q.step(st_q, ids, pos)
            lq = dec_q.last_logits[:2].copy()
            max_delta = max(max_delta,
                            float(np.max(np.abs(lf - lq))))
            mismatch += int((nf[:2] != nq[:2]).sum())
            total += 2
            ids[:2] = nf[:2]        # teacher-force the fp32 choice
            pos += 1
    rate = mismatch / total
    if rate > 0.02:
        problems.append(
            f"int8 KV cache diverged: {mismatch}/{total} tokens "
            f"differ from fp32 (bound 2%); max logit delta "
            f"{max_delta:.4f}")
    fb, qb = dec_f.kv_cache_bytes(), dec_q.kv_cache_bytes()
    if qb >= fb:
        problems.append(f"int8 KV cache is not smaller: {qb} vs "
                        f"{fb} bytes")
    return {"token_mismatch_rate": round(rate, 4),
            "max_logit_delta": round(max_delta, 6),
            "kv_bytes_fp32": fb, "kv_bytes_int8": qb,
            "kv_ratio": round(qb / fb, 3)}


def _farm_chaos_leg(problems, cfg, params, maxlen, buckets):
    """Leg 3: worker_crash on replica 0 of 2 (threaded) — the group
    must serve every request anyway: router skips the dead replica,
    GroupFuture resubmits the crashed ones, no slot leaks."""
    import numpy as np
    from paddle_tpu.resilience import chaos as _chaos

    slots = 4
    group = _farm_group(cfg, params, replicas=2, slots=slots,
                        maxlen=maxlen, buckets=buckets,
                        name="chaosfarm", retries=2)
    rng = np.random.RandomState(29)
    reqs = _decode_requests(rng, 6, maxlen, cfg.trg_vocab,
                            group.replicas[0].engine.max_new_tokens)
    _chaos.configure("worker_crash:at=2,replica=0")
    try:
        futures = [group.submit(src, src_len=n, max_new_tokens=mn)
                   for src, n, mn in reqs]
        group.start()
        served = 0
        for f in futures:
            try:
                r = f.result(timeout=60.0)
                if len(r.tokens) > 0:
                    served += 1
            except Exception as e:      # noqa: BLE001 — a drop
                problems.append(f"farm chaos leg dropped a request: "
                                f"{type(e).__name__}: {e}")
    finally:
        _chaos.reset()
        group.stop(drain=True, timeout=10.0)
    restarts = [r.scheduler.restarts for r in group.replicas]
    if restarts[0] < 1:
        problems.append("chaos worker_crash replica=0 never fired "
                        f"(restarts {restarts})")
    if served != len(reqs):
        problems.append(f"one-replica-down served {served}/"
                        f"{len(reqs)} — the group dropped requests")
    for r in group.replicas:
        r.scheduler.pool.check()
    return {"requests": len(reqs), "served": served,
            "restarts": restarts}


def _farm_rolling_leg(problems, cfg, params, maxlen):
    """Leg 4: rolling weight update under live traffic — zero dropped
    requests, both versions observed serving mid-update, zero new
    compiles from the weight swap."""
    import numpy as np

    slots = 2
    group = _farm_group(cfg, params, replicas=2, slots=slots,
                        maxlen=maxlen, buckets=(1, 2),
                        name="rollfarm", max_queue=64).start()
    params2 = {k: (v + 0.05 * np.random.RandomState(99)
                   .randn(*v.shape)).astype(v.dtype)
               for k, v in params.items()}
    rng = np.random.RandomState(41)
    reqs = _decode_requests(rng, 32, maxlen, cfg.trg_vocab, 8)
    stop = threading.Event()
    lock = threading.Lock()
    completed, errors = [0], []

    def client(wid):
        i = wid
        while not stop.is_set():
            src, n, mn = reqs[i % len(reqs)]
            i += 4
            try:
                group.submit(src, src_len=n,
                             max_new_tokens=mn).result(timeout=30.0)
                with lock:
                    completed[0] += 1
            except Exception as e:      # noqa: BLE001 — a drop
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return

    versions_seen = set()

    def watcher():
        while not stop.is_set():
            versions_seen.add(
                tuple(r.version for r in group.replicas))
            time.sleep(0.0002)

    threads = [threading.Thread(target=client, args=(w,), daemon=True)
               for w in range(4)]
    threads.append(threading.Thread(target=watcher, daemon=True))
    pre_compiles = group.compile_count
    for t in threads:
        t.start()
    try:
        time.sleep(0.2)
        group.rolling_update(params=params2, drain_timeout=30.0)
        time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(10.0)
        group.stop(drain=True, timeout=10.0)
    if errors:
        problems.append(f"rolling update dropped {len(errors)} "
                        f"requests (first: {errors[0]})")
    mixed = any(len(set(v)) == 2 for v in versions_seen)
    if not mixed:
        problems.append(
            f"rolling update never served both versions at once "
            f"(version snapshots: {sorted(versions_seen)})")
    if group.version != 2 or any(r.version != 2
                                 for r in group.replicas):
        problems.append("rolling update did not land version 2 on "
                        "every replica")
    if group.compile_count != pre_compiles:
        problems.append(
            f"rolling update recompiled "
            f"({group.compile_count - pre_compiles} new executables "
            f"— the weight swap must reuse the traces)")
    return {"completed": completed[0], "dropped": len(errors),
            "mixed_versions_observed": mixed,
            "version_snapshots": sorted(versions_seen)}


def _farm_selftest_problems(problems):
    """The tpufarm CI gate: replica-group parity + compile pin, int8
    KV parity bound, one-replica-down chaos, rolling update."""
    maxlen, buckets = 16, (1, 2, 4)
    cfg, exe, infer, logits, params = _decode_stack(maxlen=maxlen)
    info = {"parity": _farm_parity_leg(problems, cfg, exe, infer,
                                       logits, params, maxlen,
                                       buckets),
            "int8_kv": _farm_int8_leg(problems, cfg, params, maxlen),
            "chaos": _farm_chaos_leg(problems, cfg, params, maxlen,
                                     buckets),
            "rolling": _farm_rolling_leg(problems, cfg, params,
                                         maxlen)}
    return info


def run_selftest_farm(args):
    from paddle_tpu import telemetry
    telemetry.enable()
    problems = []
    info = _farm_selftest_problems(problems)
    result = {"mode": "selftest-farm", **info,
              "problems": problems, "ok": not problems}
    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        p = info["parity"]
        q = info["int8_kv"]
        print(f"tpuserve selftest-farm: {p['compile_count']} "
              f"executables for 2 replicas, "
              f"{p['mismatches']}/{p['requests']} greedy mismatches, "
              f"int8 KV {q['kv_ratio']}x bytes "
              f"(max logit delta {q['max_logit_delta']}), chaos "
              f"served {info['chaos']['served']}/"
              f"{info['chaos']['requests']}, rolling dropped "
              f"{info['rolling']['dropped']}")
        for prob in problems:
            print(f"FAIL: {prob}", file=sys.stderr)
    return 2 if problems else 0


def run_bench_farm(args):
    """Replica-group serving across the farm axes — 1 vs 2 replicas,
    fp32 vs int8 KV, pooled vs disaggregated prefill — each as a
    closed loop at ~5x total slots. Writes BENCH_decode2.json."""
    import numpy as np
    from paddle_tpu import telemetry
    from paddle_tpu.serving import RejectedError
    telemetry.enable()

    maxlen = args.decode_max_len
    slots = args.slots
    cfg, exe, infer, logits, params = _decode_stack(maxlen=maxlen)
    rng = np.random.RandomState(23)
    # short prompts: the self-attn cache (the part int8 shrinks)
    # dominates the cross caches
    src_cap = max(4, maxlen // 2)
    reqs = _decode_requests(rng, 256, src_cap, cfg.trg_vocab,
                            maxlen - 1)

    cases = [
        ("r1_fp32_pooled", 1, None, 0),
        ("r1_int8_pooled", 1, "int8", 0),
        ("r2_fp32_pooled", 2, None, 0),
        ("r2_int8_pooled", 2, "int8", 0),
        ("r2_fp32_disagg", 2, None, 1),
        ("r2_int8_disagg", 2, "int8", 1),
    ]
    out_cases = {}
    for cname, replicas, kv, pdev in cases:
        group = _farm_group(
            cfg, params, replicas=replicas, slots=slots,
            maxlen=maxlen, buckets=None, kv_quant=kv,
            prefill_devices=pdev, name=cname,
            max_queue=8 * slots * replicas).start()
        total_slots = group.num_slots
        stop_t = time.monotonic() + args.duration
        lock = threading.Lock()
        done_tokens, rejects = [0], [0]

        def client(wid, _stop=stop_t, _g=group):
            i = wid
            while time.monotonic() < _stop:
                src, n, mn = reqs[i % len(reqs)]
                i += 5 * total_slots
                try:
                    r = _g.submit(src, src_len=n,
                                  max_new_tokens=mn).result(
                        timeout=max(5.0, args.duration))
                except RejectedError:
                    with lock:
                        rejects[0] += 1
                    time.sleep(0.002)
                    continue
                except TimeoutError:
                    continue
                with lock:
                    done_tokens[0] += len(r.tokens)

        clients = [threading.Thread(target=client, args=(w,),
                                    daemon=True)
                   for w in range(5 * total_slots)]
        t0 = time.monotonic()
        for t in clients:
            t.start()
        for t in clients:
            t.join()
        dt = time.monotonic() - t0
        group.stop(drain=False, timeout=10.0)
        # devices actually computing (each engine is pinned to one
        # decode device), not the whole owned slice
        devices = {str(r.engine.device) for r in group.replicas}
        devices |= {str(d) for d in group.prefill_devices}
        goodput = done_tokens[0] / dt
        out_cases[cname] = {
            "replicas": replicas,
            "kv_quant": kv or "fp32",
            "prefill": "disaggregated" if pdev else "pooled",
            "devices": len(devices),
            "total_slots": total_slots,
            "slots_per_device": round(total_slots / len(devices), 3),
            "goodput_tokens_per_s": round(goodput, 1),
            "goodput_per_device": round(goodput / len(devices), 1),
            "kv_cache_bytes_per_replica":
                group.replicas[0].engine.kv_cache_bytes,
            "completed_tokens": done_tokens[0],
            "rejected": rejects[0],
            "compile_count": group.compile_count,
        }
        if not args.as_json:
            c = out_cases[cname]
            print(f"  {cname:<16} {c['goodput_tokens_per_s']:>8} "
                  f"tok/s  {c['goodput_per_device']:>8} tok/s/dev  "
                  f"{c['slots_per_device']:>5} slots/dev  KV "
                  f"{c['kv_cache_bytes_per_replica']} B")

    curves = {}
    for kv in ("fp32", "int8"):
        for pf in ("pooled", "disaggregated"):
            pts = sorted(
                ({"replicas": c["replicas"],
                  "slots_per_device": c["slots_per_device"],
                  "goodput_per_device": c["goodput_per_device"]}
                 for c in out_cases.values()
                 if c["kv_quant"] == kv and c["prefill"] == pf),
                key=lambda p: p["replicas"])
            if pts:
                curves[f"{kv}_{pf}"] = pts
    result = {"mode": "bench-farm", "model": "transformer-tiny",
              "maxlen": maxlen, "slots_per_replica": slots,
              "duration_s": args.duration, "cases": out_cases,
              "curves": curves}
    out_path = os.path.join(_REPO, "BENCH_decode2.json")
    try:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
    except OSError:
        pass
    if args.as_json:
        print(json.dumps(result))
    return 0


# ------------------------------------------------------------------ guard
def _guard_latency_phase(group, reqs, expected, problems, label,
                         threads=4, timeout=30.0):
    """Closed-loop clients over a STARTED group: every request's
    latency recorded, every token sequence checked against the
    precomputed greedy_decode reference. Returns sorted latencies."""
    import numpy as np
    lock = threading.Lock()
    lats, errs, mism = [], [], [0]

    def client(wid):
        for i in range(wid, len(reqs), threads):
            src, n, mn = reqs[i]
            t0 = time.monotonic()
            try:
                r = group.submit(src, src_len=n,
                                 max_new_tokens=mn).result(
                    timeout=timeout)
            except Exception as e:  # noqa: BLE001 — a drop
                with lock:
                    errs.append(f"{type(e).__name__}: {e}")
                continue
            dt = time.monotonic() - t0
            with lock:
                lats.append(dt)
                if not np.array_equal(
                        np.asarray(r.tokens, np.int64), expected[i]):
                    mism[0] += 1

    ts = [threading.Thread(target=client, args=(w,), daemon=True)
          for w in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout + 30.0)
    if errs:
        problems.append(f"guard {label}: dropped {len(errs)}/"
                        f"{len(reqs)} requests (first: {errs[0]})")
    if mism[0]:
        problems.append(
            f"guard {label}: {mism[0]}/{len(reqs)} outputs differ "
            f"from greedy_decode — hedging/cancellation changed "
            f"the tokens")
    return sorted(lats)


def _hedge_guard_config(**over):
    """Hedging isolated: health transitions and brownout are pushed
    out of reach so any p99 win is attributable to the hedge alone."""
    from paddle_tpu.serving.guard import GuardConfig
    kw = dict(hedge_fixed_delay_s=0.05, hedge_fraction=1.0,
              hedge_burst=64.0, retry_rate=1000.0, retry_burst=1000,
              slow_factor=1e9, err_probation=2.0, enter_streak=10**6,
              queue_high=10**9)
    kw.update(over)
    return GuardConfig(**kw)


def _guard_hedge_leg(problems, cfg, exe, infer, logits, params,
                     maxlen, buckets):
    """Leg (a): replica_slow on 1 of 2 replicas — hedged requests must
    cut p99 vs the guard-off group under the SAME fault, at token
    parity with greedy_decode, with every losing leg's slot
    reclaimed."""
    import numpy as np
    from paddle_tpu.models.transformer import greedy_decode
    from paddle_tpu.resilience import chaos as _chaos

    slots = 4
    rng = np.random.RandomState(17)
    reqs = _decode_requests(rng, 24, maxlen, cfg.trg_vocab, 6)
    expected = []
    for src, n, max_new in reqs:
        row = np.zeros((1, maxlen), np.int64)
        row[0, :n] = src
        ids = greedy_decode(exe, infer, logits, row,
                            np.array([n], "int64"), bos=0,
                            fetch_argmax=True)
        expected.append(ids[0, 1:1 + max_new])

    out = {}
    for label, guard in (("off", None), ("hedged",
                                         _hedge_guard_config())):
        group = _farm_group(cfg, params, replicas=2, slots=slots,
                            maxlen=maxlen, buckets=buckets,
                            name=f"guard-{label}", retries=2,
                            guard=guard).start()
        _chaos.configure("replica_slow:ms=120,replica=0")
        try:
            lats = _guard_latency_phase(group, reqs, expected,
                                        problems, label)
        finally:
            _chaos.reset()
            group.stop(drain=True, timeout=15.0)
        for r in group.replicas:
            r.scheduler.pool.check()
            if r.scheduler.pool.free_count() != slots:
                problems.append(f"guard {label}: replica {r.index} "
                                f"leaked slots")
        case = {"requests": len(lats),
                "p50_ms": round(1000 * _percentile(lats, 0.50), 2)
                if lats else None,
                "p99_ms": round(1000 * _percentile(lats, 0.99), 2)
                if lats else None}
        if guard is not None:
            g = group.guard
            case.update(hedges=g.hedges, hedge_wins=g.hedge_wins,
                        hedge_cancelled=g.hedge_cancelled)
            if g.hedges < 1:
                problems.append("hedging never fired under "
                                "replica_slow")
            if g.hedge_wins < 1:
                problems.append("no hedge ever won the race against "
                                "the throttled primary")
        out[label] = case
    off, on = out["off"]["p99_ms"], out["hedged"]["p99_ms"]
    if off is not None and off < 200.0:
        problems.append(f"replica_slow did not bite: guard-off p99 "
                        f"{off}ms (expected a throttled tail)")
    if off is not None and on is not None and on >= 0.7 * off:
        problems.append(
            f"hedging did not cut p99: {on}ms hedged vs {off}ms "
            f"guard-off under the same replica_slow fault")
    return out


def _pump_guard(group, futs, problems, label, budget=600):
    """Drive a non-started guarded group until every future resolves,
    catching injected crashes the way the supervisor thread would.
    Returns {index: DecodeResult}; drops land in `problems`."""
    from paddle_tpu.resilience.chaos import ChaosFault
    results, pending, left = {}, dict(enumerate(futs)), budget
    while pending and left:
        left -= 1
        for i, f in list(pending.items()):
            try:
                results[i] = f.result(timeout=0)
                del pending[i]
            except TimeoutError:
                pass            # resubmitted / still decoding
            except Exception as e:  # noqa: BLE001 — a drop
                problems.append(f"guard {label} dropped a request: "
                                f"{type(e).__name__}: {e}")
                del pending[i]
        if not pending:
            break
        for r in group.replicas:
            try:
                r.scheduler.run_iteration()
            except ChaosFault as e:
                r.scheduler._crash_recover(e)
                r.scheduler.restarts += 1
    if pending:
        problems.append(f"guard {label}: {len(pending)} requests "
                        f"never completed in {budget} iterations")
    return results


def _guard_flap_leg(problems, cfg, params, maxlen, buckets):
    """Leg (b): a crash-flapping replica must be walked to EJECTED,
    probed after cooldown, and re-admitted — with zero dropped
    requests along the way. Manually driven: the flap is armed only
    once slots are bound, so the walk is deterministic."""
    import numpy as np
    from paddle_tpu.resilience import chaos as _chaos
    from paddle_tpu.resilience.chaos import ChaosFault
    from paddle_tpu.serving.guard import GuardConfig

    # trip-sensitive health for CI clocks: the first crash-failed leg
    # puts replica 0 on probation, the second consecutive one ejects
    # it (a real deployment would ride the defaults' longer streaks)
    gcfg = GuardConfig(hedge=False, slow_factor=1e9, min_samples=1,
                       enter_streak=1, probation_grace=1,
                       err_probation=0.25, err_exit=0.6,
                       probation_good=1, cooldown_s=0.25,
                       cooldown_max_s=2.0, retry_rate=200.0,
                       retry_burst=200, queue_high=10**9)
    group = _farm_group(cfg, params, replicas=2, slots=4,
                        maxlen=maxlen, buckets=buckets,
                        name="guard-flap", retries=4, guard=gcfg)
    health = group.guard.health
    rng = np.random.RandomState(19)
    reqs = _decode_requests(rng, 12, maxlen, cfg.trg_vocab, 5)

    # 4 submissions alternate r0/r1 under least-loaded scoring; admit
    # them into slots BEFORE arming the flap so the burst has legs to
    # kill (the chaos check runs before admission, so queued-only work
    # never dies with a replica)
    futs = [group.submit(src, src_len=n, max_new_tokens=mn)
            for src, n, mn in reqs[:4]]
    legs0 = sum(1 for f in futs if f.replica_index == 0)
    if legs0 < 2:
        problems.append(f"flap precondition: expected 2 legs routed "
                        f"to replica 0, got {legs0}")
    group.run_iteration()
    _chaos.configure("replica_flap:at=1,times=2,replica=0")
    try:
        r0 = group.replicas[0]
        try:
            r0.scheduler.run_iteration()
            problems.append("replica_flap never fired on the bound "
                            "slots")
        except ChaosFault as e:
            r0.scheduler._crash_recover(e)
            r0.scheduler.restarts += 1
        # polling the dead legs immediately (pure Python, well inside
        # the cooldown window) feeds the health tracker: first error
        # -> probation, second consecutive -> EJECTED; both requests
        # resubmit to replica 1 — zero drops
        for f in futs:
            try:
                f.result(timeout=0)
            except TimeoutError:
                pass
        if health.ejections < 1 or health.state(0) != "ejected":
            problems.append(
                f"flapping replica was not ejected (state "
                f"{health.state(0)!r}, ejections "
                f"{health.ejections})")
        # while ejected the router must never select replica 0
        mid = [group.submit(src, src_len=n, max_new_tokens=mn)
               for src, n, mn in reqs[4:8]]
        if any(f.replica_index == 0 for f in mid):
            problems.append("router sent traffic to an EJECTED "
                            "replica")
        _pump_guard(group, futs + mid, problems, "flap-mid",
                    budget=400)
        # cooldown passes -> HALF_OPEN; the next request IS the probe.
        # The flap still has one charge: the probe rides through a
        # respawn (the crash fires before admission, so the probe
        # survives queued), then completes as the OK sample that
        # re-admits the replica
        time.sleep(0.3)
        src, n, mn = reqs[8]
        probe = group.submit(src, src_len=n, max_new_tokens=mn)
        if probe.replica_index != 0:
            problems.append(
                f"half-open probe was not routed to the cooled-down "
                f"replica (went to {probe.replica_index})")
        if health.probes < 1:
            problems.append("probe routing did not consume probe "
                            "capacity")
        _pump_guard(group, [probe], problems, "flap-probe",
                    budget=400)
    finally:
        _chaos.reset()
    if health.readmissions < 1 or health.state(0) != "healthy":
        problems.append(
            f"probed replica was not re-admitted (state "
            f"{health.state(0)!r}, readmissions "
            f"{health.readmissions})")
    for r in group.replicas:
        r.scheduler.pool.check()
        if r.scheduler.pool.free_count() != 4:
            problems.append(f"flap leg: replica {r.index} leaked "
                            f"slots")
    return {"served": 9, "ejections": health.ejections,
            "probes": health.probes,
            "readmissions": health.readmissions,
            "replica0_restarts": group.replicas[0].scheduler.restarts,
            "final_states": [health.state(r.index)
                             for r in group.replicas]}


def _guard_poison_leg(problems, cfg, params, maxlen, buckets):
    """Leg (c): request_poison kills whichever replica steps it — the
    poisoned request must fail ALONE (typed, after its retries burn
    out), innocents ride resubmission, the blasted replicas survive
    probation without ejection, and no slot leaks."""
    import numpy as np
    from paddle_tpu.resilience import chaos as _chaos
    from paddle_tpu.serving.guard import GuardConfig

    slots = 4
    gcfg = GuardConfig(hedge=False, slow_factor=1e9, enter_streak=3,
                       probation_grace=10, err_probation=0.35,
                       retry_rate=200.0, retry_burst=200,
                       queue_high=10**9)
    group = _farm_group(cfg, params, replicas=2, slots=slots,
                        maxlen=maxlen, buckets=buckets,
                        name="guard-poison", retries=3,
                        guard=gcfg).start()
    rng = np.random.RandomState(31)
    reqs = _decode_requests(rng, 8, maxlen, cfg.trg_vocab, 5)
    poison_i = 2
    _chaos.configure(f"request_poison:at={poison_i + 1}")
    outcomes = []
    try:
        futures = [group.submit(src, src_len=n, max_new_tokens=mn)
                   for src, n, mn in reqs]
        for f in futures:
            try:
                r = f.result(timeout=30.0)
                outcomes.append(("ok", len(r.tokens)))
            except Exception as e:  # noqa: BLE001 — expected once
                outcomes.append(("err", type(e).__name__))
    finally:
        _chaos.reset()
    failed = [i for i, o in enumerate(outcomes) if o[0] == "err"]
    if failed != [poison_i]:
        problems.append(
            f"request_poison blast was not contained: requests "
            f"{failed} failed, expected exactly [{poison_i}] "
            f"(outcomes: {outcomes})")
    health = group.guard.health
    if health.ejections:
        problems.append("a single poisoned request got a replica "
                        "ejected (poison != sick replica)")
    # recovery wave: both replicas must still serve after the blast
    recovered = 0
    for src, n, mn in reqs[:4]:
        try:
            group.submit(src, src_len=n,
                         max_new_tokens=mn).result(timeout=30.0)
            recovered += 1
        except Exception as e:  # noqa: BLE001 — a drop
            problems.append(f"post-poison request dropped: "
                            f"{type(e).__name__}: {e}")
    group.stop(drain=True, timeout=15.0)
    for r in group.replicas:
        r.scheduler.pool.check()
        if r.scheduler.pool.free_count() != slots:
            problems.append(f"poison leg: replica {r.index} leaked "
                            f"slots")
    return {"outcomes": outcomes,
            "failed": failed,
            "recovered_wave": recovered,
            "restarts": [r.scheduler.restarts
                         for r in group.replicas],
            "resubmits": group.guard.resubmits,
            "final_states": [health.state(r.index)
                             for r in group.replicas]}


def _guard_brownout_leg(problems, cfg, params, maxlen, buckets):
    """Leg (d): synthetic overload — brownout sheds ONLY the lowest
    QoS class (with a Retry-After hint), clamps the survivors'
    generation length, recovers hysteretically; then a crash storm
    shows the retry budget capping resubmissions with a typed error."""
    import numpy as np
    from paddle_tpu.resilience import chaos as _chaos
    from paddle_tpu.resilience.chaos import ChaosFault
    from paddle_tpu.serving import RetryBudgetExhausted
    from paddle_tpu.serving.batcher import BrownoutShed
    from paddle_tpu.serving.decode import QosPolicy
    from paddle_tpu.serving.guard import GuardConfig

    # --- brownout: shed the batch class, clamp interactive, recover
    gcfg = GuardConfig(hedge=False, slow_factor=1e9, queue_high=6,
                       queue_low=1, dwell_s=0.05, clamp_new_tokens=3,
                       retry_after_s=2.5, retry_rate=200.0,
                       retry_burst=200, enter_streak=10**6)
    group = _farm_group(
        cfg, params, replicas=1, slots=4, maxlen=maxlen,
        buckets=buckets, name="guard-brownout", guard=gcfg,
        qos_factory=lambda: QosPolicy(
            tenants=[("interactive", 4.0), ("batch", 1.0)]))
    rng = np.random.RandomState(37)
    reqs = _decode_requests(rng, 12, maxlen, cfg.trg_vocab, 4)
    futs, shed = [], None
    for k in range(8):
        src, n, mn = reqs[k]
        try:
            futs.append(group.submit(src, src_len=n, tenant="batch",
                                     max_new_tokens=mn))
        except BrownoutShed as e:
            shed = e
    bo = group.guard.brownout
    if shed is None:
        problems.append("brownout never shed the batch class under "
                        "a flooded queue")
    elif shed.retry_after_s != 2.5:
        problems.append(f"BrownoutShed lost the Retry-After hint: "
                        f"{shed.retry_after_s}")
    if not bo.active:
        problems.append("brownout controller not active at "
                        "queue_high")
    # the paying class rides through, generation length clamped
    src, n, _ = reqs[8]
    fi = group.submit(src, src_len=n, tenant="interactive",
                      max_new_tokens=8)
    if bo.clamped < 1:
        problems.append("brownout did not clamp the interactive "
                        "class's max_new_tokens")
    futs.append(fi)
    pending = dict(enumerate(futs))
    interactive_tokens = None
    for _ in range(600):
        if not pending:
            break
        group.run_iteration()
        for i, f in list(pending.items()):
            try:
                r = f.result(timeout=0)
            except TimeoutError:
                continue
            if f is fi:
                interactive_tokens = len(r.tokens)
            del pending[i]
    if pending:
        problems.append(f"brownout leg: {len(pending)} requests "
                        f"never completed")
    if interactive_tokens is not None and interactive_tokens > 3:
        problems.append(f"clamped interactive request generated "
                        f"{interactive_tokens} tokens (clamp 3)")
    time.sleep(0.06)        # past the hysteresis dwell, queue empty
    src, n, mn = reqs[9]
    try:
        f2 = group.submit(src, src_len=n, tenant="batch",
                          max_new_tokens=mn)
    except BrownoutShed:
        f2 = None
        problems.append("brownout failed to recover: batch class "
                        "still shed on an empty queue")
    if bo.active:
        problems.append("brownout still active after recovery "
                        "conditions were met")
    if f2 is not None:
        for _ in range(200):
            group.run_iteration()
            try:
                f2.result(timeout=0)
                break
            except TimeoutError:
                continue
        else:
            problems.append("post-recovery batch request never "
                            "completed")
    brown = {"entries": bo.entries, "sheds": bo.sheds,
             "clamped": bo.clamped, "recovered": not bo.active,
             "retry_after_s": None if shed is None
             else shed.retry_after_s}

    # --- retry budget: a crash storm is capped by the token bucket,
    # not by the per-request retry count (10 here)
    group2 = _farm_group(cfg, params, replicas=3, slots=2,
                         maxlen=maxlen, buckets=buckets,
                         name="guard-storm", retries=10,
                         guard=GuardConfig(hedge=False,
                                           slow_factor=1e9,
                                           retry_rate=0.0,
                                           retry_burst=2,
                                           queue_high=10**9))
    src, n, _ = reqs[10]
    _chaos.configure("worker_crash:every=2")
    typed = None
    try:
        f = group2.submit(src, src_len=n, max_new_tokens=3)
        for _ in range(200):
            for r in group2.replicas:
                try:
                    r.scheduler.run_iteration()
                except ChaosFault as e:
                    r.scheduler._crash_recover(e)
                    r.scheduler.restarts += 1
            try:
                f.result(timeout=0)
                problems.append("crash-storm request completed — "
                                "worker_crash:every=2 never fired")
                break
            except TimeoutError:
                continue
            except RetryBudgetExhausted as e:
                typed = e
                break
            except Exception as e:  # noqa: BLE001 — wrong type
                problems.append(
                    f"retry-budget exhaustion raised "
                    f"{type(e).__name__}, expected "
                    f"RetryBudgetExhausted: {e}")
                break
    finally:
        _chaos.reset()
    if typed is None and not problems:
        problems.append("retry budget never produced a typed "
                        "RetryBudgetExhausted under the crash storm")
    g2 = group2.guard
    if g2.resubmits != 2:
        problems.append(f"retry budget (burst 2) allowed "
                        f"{g2.resubmits} resubmissions, expected "
                        f"exactly 2")
    for r in group2.replicas:
        r.scheduler.pool.check()
    return {"brownout": brown,
            "retry_budget": {"typed": typed is not None,
                             "resubmits": g2.resubmits,
                             "denied": g2.retry_budget.denied}}


def _guard_selftest_problems(problems):
    """The tpuguard CI gate: hedging under replica_slow, flap
    ejection/re-admission, poison containment, brownout + retry
    budget."""
    maxlen, buckets = 16, (1, 2, 4)
    cfg, exe, infer, logits, params = _decode_stack(maxlen=maxlen)
    info = {"hedge": _guard_hedge_leg(problems, cfg, exe, infer,
                                      logits, params, maxlen,
                                      buckets),
            "flap": _guard_flap_leg(problems, cfg, params, maxlen,
                                    buckets),
            "poison": _guard_poison_leg(problems, cfg, params, maxlen,
                                        buckets),
            "overload": _guard_brownout_leg(problems, cfg, params,
                                            maxlen, buckets)}
    return info


def run_selftest_guard(args):
    from paddle_tpu import telemetry
    telemetry.enable()
    problems = []
    info = _guard_selftest_problems(problems)
    result = {"mode": "selftest-guard", **info,
              "problems": problems, "ok": not problems}
    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        h = info["hedge"]
        fl = info["flap"]
        ov = info["overload"]
        print(f"tpuserve selftest-guard: hedged p99 "
              f"{h['hedged']['p99_ms']}ms vs {h['off']['p99_ms']}ms "
              f"guard-off ({h['hedged'].get('hedges', 0)} hedges, "
              f"{h['hedged'].get('hedge_wins', 0)} wins); flap "
              f"ejections={fl['ejections']} probes={fl['probes']} "
              f"readmissions={fl['readmissions']} "
              f"dropped={fl['dropped']}; poison failed "
              f"{info['poison']['failed']}; brownout sheds="
              f"{ov['brownout']['sheds']} clamped="
              f"{ov['brownout']['clamped']} recovered="
              f"{ov['brownout']['recovered']}; retry resubmits="
              f"{ov['retry_budget']['resubmits']}")
        for prob in problems:
            print(f"FAIL: {prob}", file=sys.stderr)
    return 2 if problems else 0


def run_bench_guard(args):
    """Tail-latency defense bench: closed-loop p50/p99 with and
    without hedging while replica_slow throttles 1 of 2 replicas.
    Writes BENCH_guard.json and appends guard_* records to the
    paddle_tpu.bench.history.v1 spine for the tpustat --slo gate."""
    import numpy as np
    from paddle_tpu import telemetry
    from paddle_tpu.resilience import chaos as _chaos
    telemetry.enable()

    maxlen = args.decode_max_len
    slots = args.slots
    cfg, exe, infer, logits, params = _decode_stack(maxlen=maxlen)
    rng = np.random.RandomState(43)
    reqs = _decode_requests(rng, 128, max(4, maxlen // 2),
                            cfg.trg_vocab, 8)
    out_cases = {}
    for label, guard in (("guard_off", None),
                         ("guard_hedged", _hedge_guard_config())):
        group = _farm_group(cfg, params, replicas=2, slots=slots,
                            maxlen=maxlen, buckets=None, name=label,
                            retries=2, guard=guard,
                            max_queue=16 * slots).start()
        _chaos.configure("replica_slow:ms=60,replica=0")
        stop_t = time.monotonic() + args.duration
        lock = threading.Lock()
        lats, drops = [], [0]

        def client(wid, _stop=stop_t, _g=group):
            i = wid
            while time.monotonic() < _stop:
                src, n, mn = reqs[i % len(reqs)]
                i += 4 * slots
                t0 = time.monotonic()
                try:
                    _g.submit(src, src_len=n,
                              max_new_tokens=mn).result(
                        timeout=max(5.0, args.duration))
                except Exception:  # noqa: BLE001 — count, move on
                    with lock:
                        drops[0] += 1
                    continue
                with lock:
                    lats.append(time.monotonic() - t0)

        clients = [threading.Thread(target=client, args=(w,),
                                    daemon=True)
                   for w in range(4 * slots)]
        for t in clients:
            t.start()
        for t in clients:
            t.join()
        _chaos.reset()
        group.stop(drain=True, timeout=15.0)
        lats.sort()
        case = {"requests": len(lats), "dropped": drops[0],
                "p50_ms": round(1000 * _percentile(lats, 0.50), 2)
                if lats else None,
                "p99_ms": round(1000 * _percentile(lats, 0.99), 2)
                if lats else None}
        if guard is not None:
            g = group.guard
            case.update(hedges=g.hedges, hedge_wins=g.hedge_wins,
                        hedge_cancelled=g.hedge_cancelled)
        out_cases[label] = case
        if not args.as_json:
            print(f"  {label:<14} p50 {case['p50_ms']}ms  p99 "
                  f"{case['p99_ms']}ms  ({case['requests']} requests"
                  + (f", {case['hedges']} hedges"
                     if "hedges" in case else "") + ")")

    result = {"mode": "bench-guard", "model": "transformer-tiny",
              "maxlen": maxlen, "slots_per_replica": slots,
              "duration_s": args.duration,
              "fault": "replica_slow:ms=60,replica=0",
              "cases": out_cases}
    out_path = os.path.join(_REPO, "BENCH_guard.json")
    try:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
    except OSError:
        pass
    result["history_appended"] = _guard_append_history(out_cases)
    if args.as_json:
        print(json.dumps(result))
    return 0


def _guard_append_history(cases):
    """One paddle_tpu.bench.history.v1 record per headline guard
    metric, onto the same spine bench.py feeds (BENCH_HISTORY_PATH
    overrides the repo-root default) so `tpustat --slo` regression-
    gates the hedged tail like any other perf number. Best-effort:
    returns the path or None, never raises."""
    try:
        import subprocess

        from paddle_tpu.telemetry import slo
        try:
            sha = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"], cwd=_REPO,
                capture_output=True, text=True,
                timeout=10).stdout.strip() or None
        except Exception:  # noqa: BLE001 — sha is optional
            sha = None
        common = {"schema": slo.HISTORY_SCHEMA,
                  "platform": os.environ.get("JAX_PLATFORMS", "cpu"),
                  "device_kind": "cpu", "git_sha": sha,
                  "unix_time": round(time.time(), 1),
                  "stage": "guard"}
        recs = []
        for case, key, metric in (
                ("guard_off", "p99_ms", "guard_off_p99_ms"),
                ("guard_hedged", "p99_ms", "guard_hedged_p99_ms"),
                ("guard_hedged", "p50_ms", "guard_hedged_p50_ms")):
            v = cases.get(case, {}).get(key)
            if isinstance(v, (int, float)) and v:
                recs.append(dict(common, metric=metric, value=v,
                                 unit="ms"))
        if not recs:
            return None
        path = os.environ.get("BENCH_HISTORY_PATH") \
            or os.path.join(_REPO, "BENCH_history.jsonl")
        slo.append_history(path, recs)
        return path
    except Exception:  # noqa: BLE001 — history is best-effort
        return None


# ------------------------------------------------------------------ scale
def _scale_group(cfg, params, slots, maxlen, buckets, name,
                 guard=None, qos_factory=None, max_queue=64):
    """A 1-replica group provisioned ELASTICALLY: the seed replica
    owns device 0 only, every other local device stays free for the
    planner's ledger. (A statically-provisioned group's single slice
    spans ALL devices — the planner would see free=0 and report the
    ceiling immediately; see the scale package docstring.)"""
    import jax

    from paddle_tpu.serving.decode import (DecodeConfig,
                                           DecodeEngineConfig)
    from paddle_tpu.serving.farm import FarmConfig, ReplicaGroup
    devs = jax.devices()
    group = ReplicaGroup(cfg, params, FarmConfig(
        replicas=1, devices=devs[:1],
        engine=DecodeEngineConfig(num_slots=slots, max_len=maxlen,
                                  prefill_buckets=buckets),
        decode=DecodeConfig(bos=0, max_queue_requests=max_queue),
        guard=guard, qos_factory=qos_factory), name=name)
    return group, devs


def _scale_ramp_leg(problems, cfg, params, maxlen, buckets):
    """Leg (a): a tpuchaos traffic_spike rides the queue up — the
    controller must ramp N->M (through the shared build cache: ZERO
    new compiles), serve every real request, then drain-and-shrink
    back to N once the spike passes."""
    import numpy as np

    from paddle_tpu import telemetry as _tm
    from paddle_tpu.resilience import chaos as _chaos
    from paddle_tpu.serving.batcher import RejectedError
    from paddle_tpu.serving.scale import (ScaleController, ScalePlanner,
                                          ScalePolicy)

    group, devs = _scale_group(cfg, params, slots=2, maxlen=maxlen,
                               buckets=buckets, name="scale-ramp")
    policy = ScalePolicy(
        ["queue_per_replica > 4 -> up", "queue_depth < 1 -> down"],
        min_replicas=1, max_replicas=3,
        up_cooldown_s=0.0, down_cooldown_s=0.0,
        up_dwell=1, down_dwell=2)
    ctl = ScaleController(group, policy,
                          ScalePlanner(group, devices=devs, width=1))
    c0 = group.compile_count
    rng = np.random.RandomState(53)
    reqs = _decode_requests(rng, 12, maxlen, cfg.trg_vocab, 4)
    _chaos.configure("traffic_spike:at=3,x=5,len=6")
    futs, timeline, max_live = [], [], 1
    try:
        for k, (src, n, mn) in enumerate(reqs):
            try:
                futs.append(group.submit(src, src_len=n,
                                         max_new_tokens=mn))
            except RejectedError:
                problems.append(f"scale ramp DROPPED real request "
                                f"{k} at admission")
            d = ctl.tick()
            max_live = max(max_live, len(group.replicas))
            timeline.append({"k": k, "queued": group.queued,
                             "live": len(group.replicas),
                             "action": d.action})
    finally:
        _chaos.reset()
    compiles_up = group.compile_count - c0
    t0 = time.monotonic()
    results = _pump_group(group, futs, problems, "scale-ramp",
                          budget=2000)
    drain_s = time.monotonic() - t0
    # shadows the spike injected may still be queued: drain them so
    # the down trigger (queue_depth < 1) can see a quiet group
    for _ in range(800):
        if group.queued == 0 and all(
                r.scheduler.pool.active_count() == 0
                for r in group.replicas):
            break
        group.run_iteration()
    for _ in range(8):
        d = ctl.tick(drive=True)
        timeline.append({"k": "drain", "queued": group.queued,
                         "live": len(group.replicas),
                         "action": d.action})
        if len(group.replicas) <= policy.min_replicas:
            break
    if max_live < 2:
        problems.append(f"controller never scaled up under the "
                        f"traffic spike (max live {max_live})")
    if compiles_up != 0:
        problems.append(f"scale-up RECOMPILED: compile_count grew by "
                        f"{compiles_up} (shared build cache must make "
                        f"grows free)")
    if len(group.replicas) != policy.min_replicas:
        problems.append(f"group did not shrink back to "
                        f"{policy.min_replicas} after the spike "
                        f"(live {len(group.replicas)})")
    if len(results) != len(reqs):
        problems.append(f"scale ramp served {len(results)}/"
                        f"{len(reqs)} real requests")
    tokens = sum(len(r.tokens) for r in results.values())
    shadows = _tm.counter("serving.farm.spike_shadows").value
    if shadows < 1:
        problems.append("traffic_spike fault never injected a "
                        "shadow request")
    ctl.stop()
    group.stop()
    return {"served": len(results), "dropped": len(reqs)
            - len(results), "max_live": max_live,
            "final_live": len(group.replicas),
            "scaleup_recompiles": compiles_up,
            "spike_shadows": int(shadows),
            "goodput_tokens_per_s": round(tokens / max(drain_s, 1e-6),
                                          1),
            "drain_ms": round(drain_s * 1000.0, 2),
            "decisions": dict(ctl.decisions),
            "planner": ctl.planner.stats(),
            "timeline": timeline}


def _scale_ceiling_leg(problems, cfg, params, maxlen, buckets):
    """Leg (b): shed-only-at-ceiling. While a free device slice
    exists, an overloaded guard must DEFER brownout (the controller
    relays headroom); the moment the planner/policy report the
    ceiling, brownout engages — exactly then, exactly once."""
    import numpy as np

    from paddle_tpu.serving.batcher import BrownoutShed
    from paddle_tpu.serving.decode import QosPolicy
    from paddle_tpu.serving.guard import GuardConfig
    from paddle_tpu.serving.scale import (ScaleController, ScalePlanner,
                                          ScalePolicy)

    gcfg = GuardConfig(hedge=False, slow_factor=1e9, queue_high=4,
                       queue_low=1, dwell_s=0.01, retry_after_s=1.5,
                       retry_rate=200.0, retry_burst=200,
                       enter_streak=10**6)
    group, devs = _scale_group(
        cfg, params, slots=2, maxlen=maxlen, buckets=buckets,
        name="scale-ceiling", guard=gcfg,
        qos_factory=lambda: QosPolicy(
            tenants=[("interactive", 4.0), ("batch", 1.0)]))
    policy = ScalePolicy(["queue_depth > 4 -> up"], min_replicas=1,
                         max_replicas=2, up_cooldown_s=0.0,
                         up_dwell=1)
    ctl = ScaleController(group, policy,
                          ScalePlanner(group, devices=devs, width=1))
    bo = group.guard.brownout
    ctl.tick()                      # below the ceiling: headroom on
    if not bo.headroom:
        problems.append("controller did not relay headroom to the "
                        "guard below the ceiling")
    rng = np.random.RandomState(59)
    reqs = _decode_requests(rng, 14, maxlen, cfg.trg_vocab, 3)
    futs, early_shed = [], 0
    for k in range(7):              # flood: queue >= queue_high
        src, n, mn = reqs[k]
        try:
            futs.append(group.submit(src, src_len=n, tenant="batch",
                                     max_new_tokens=mn))
        except BrownoutShed:
            early_shed += 1
    deferred_below = bo.deferred
    if early_shed:
        problems.append(f"brownout shed {early_shed} request(s) "
                        f"while a free device slice existed")
    if bo.entries != 0:
        problems.append("brownout ENGAGED below the device ceiling "
                        "(scale-out must beat shedding)")
    if deferred_below < 1:
        problems.append("brownout entry was never deferred under "
                        "overload with headroom")
    d = ctl.tick()                  # grow 1->2; now at policy ceiling
    if d.action != "up":
        problems.append(f"overloaded controller decided "
                        f"{d.action!r}, expected 'up'")
    if not d.at_ceiling:
        problems.append("grow to max_replicas did not report the "
                        "ceiling")
    if bo.headroom:
        problems.append("headroom still on at the ceiling — brownout "
                        "deferral never lifts")
    sheds_at_ceiling = 0
    for k in range(7, 11):          # still flooded, no slices left
        src, n, mn = reqs[k]
        try:
            futs.append(group.submit(src, src_len=n, tenant="batch",
                                     max_new_tokens=mn))
        except BrownoutShed:
            sheds_at_ceiling += 1
    if bo.entries != 1:
        problems.append(f"brownout entries={bo.entries} at the "
                        f"ceiling, expected exactly 1")
    if sheds_at_ceiling < 1:
        problems.append("brownout never shed at the device ceiling")
    src, n, _ = reqs[11]            # the paying class rides through
    try:
        futs.append(group.submit(src, src_len=n, tenant="interactive",
                                 max_new_tokens=3))
    except BrownoutShed:
        problems.append("brownout shed the interactive class")
    _pump_guard(group, futs, problems, "scale-ceiling", budget=800)
    ctl.stop()
    group.stop()
    return {"deferred_below_ceiling": deferred_below,
            "entries": bo.entries, "sheds": bo.sheds,
            "sheds_at_ceiling": sheds_at_ceiling,
            "early_sheds": early_shed,
            "grew_to": len(group.replicas),
            "decisions": dict(ctl.decisions)}


def _scale_gate_leg(problems, cfg, params, maxlen, buckets):
    """Leg (c): growing re-runs the meshlint pre-spawn gate — a plan
    whose per-replica KV footprint exceeds PADDLE_TPU_DEVICE_MEM_CAP
    is REJECTED before any engine is built."""
    from paddle_tpu.serving.scale import (ScalePlanner,
                                          ScalePlanRejected)

    group, devs = _scale_group(cfg, params, slots=2, maxlen=maxlen,
                               buckets=buckets, name="scale-gate")
    planner = ScalePlanner(group, devices=devs, width=1)
    live0 = len(group.replicas)
    old = os.environ.get("PADDLE_TPU_DEVICE_MEM_CAP")
    # the cap env var is in MiB; 0.01 MiB is far below the tiny
    # model's per-replica KV floor, so the plan must be rejected
    os.environ["PADDLE_TPU_DEVICE_MEM_CAP"] = "0.01"
    rejected = None
    try:
        try:
            planner.grow(1)
            problems.append("planner grew past a 0.01 MiB device "
                            "mem cap — the verify gate did not run")
        except ScalePlanRejected as e:
            rejected = e
    finally:
        if old is None:
            os.environ.pop("PADDLE_TPU_DEVICE_MEM_CAP", None)
        else:
            os.environ["PADDLE_TPU_DEVICE_MEM_CAP"] = old
    if rejected is not None and rejected.reason != "verify":
        problems.append(f"grow rejection reason "
                        f"{rejected.reason!r}, expected 'verify'")
    if len(group.replicas) != live0:
        problems.append("a rejected grow still changed the live "
                        "replica count")
    ok = None
    try:                            # cap restored: the same plan goes
        planner.grow(1)
        ok = len(group.replicas)
    except ScalePlanRejected as e:
        problems.append(f"grow rejected with the cap restored: {e}")
    if ok is not None and ok != live0 + 1:
        problems.append(f"post-gate grow left {ok} replicas, "
                        f"expected {live0 + 1}")
    group.stop()
    return {"rejected": rejected is not None,
            "reason": None if rejected is None else rejected.reason,
            "rejections": planner.rejections,
            "live_after": len(group.replicas)}


def _scale_selftest_problems(problems):
    """The tpuscale CI gate: spike ramp with zero drops and zero
    scale-up recompiles, shed-only-at-ceiling, verify-gated grows."""
    maxlen, buckets = 16, (1, 2, 4)
    cfg, exe, infer, logits, params = _decode_stack(maxlen=maxlen)
    return {"ramp": _scale_ramp_leg(problems, cfg, params, maxlen,
                                    buckets),
            "ceiling": _scale_ceiling_leg(problems, cfg, params,
                                          maxlen, buckets),
            "gate": _scale_gate_leg(problems, cfg, params, maxlen,
                                    buckets)}


def _scale_write_bench(section, payload):
    """Merge one section into BENCH_autoscale.json (selftest and
    bench write different halves of the same artifact)."""
    out_path = os.path.join(_REPO, "BENCH_autoscale.json")
    data = {}
    try:
        with open(out_path) as f:
            data = json.load(f)
    except Exception:  # noqa: BLE001 — first write / stale file
        data = {}
    data["schema"] = "paddle_tpu.bench.autoscale.v1"
    data[section] = payload
    try:
        with open(out_path, "w") as f:
            json.dump(data, f, indent=2)
    except OSError:
        return None
    return out_path


def _scale_append_history(ramp):
    """autoscale_* records onto the bench history spine (same shape
    as _guard_append_history; `tpustat --slo` gates them: goodput is
    higher-better, _ms lower-better). Best-effort."""
    try:
        import subprocess

        from paddle_tpu.telemetry import slo
        try:
            sha = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"], cwd=_REPO,
                capture_output=True, text=True,
                timeout=10).stdout.strip() or None
        except Exception:  # noqa: BLE001 — sha is optional
            sha = None
        common = {"schema": slo.HISTORY_SCHEMA,
                  "platform": os.environ.get("JAX_PLATFORMS", "cpu"),
                  "device_kind": "cpu", "git_sha": sha,
                  "unix_time": round(time.time(), 1),
                  "stage": "scale"}
        recs = []
        for key, metric, unit in (
                ("goodput_tokens_per_s", "autoscale_spike_goodput_tps",
                 "tokens/s"),
                ("drain_ms", "autoscale_spike_drain_ms", "ms")):
            v = ramp.get(key)
            if isinstance(v, (int, float)) and v:
                recs.append(dict(common, metric=metric, value=v,
                                 unit=unit))
        if not recs:
            return None
        path = os.environ.get("BENCH_HISTORY_PATH") \
            or os.path.join(_REPO, "BENCH_history.jsonl")
        slo.append_history(path, recs)
        return path
    except Exception:  # noqa: BLE001 — history is best-effort
        return None


def run_selftest_scale(args):
    from paddle_tpu import telemetry
    telemetry.enable()
    problems = []
    info = _scale_selftest_problems(problems)
    result = {"mode": "selftest-scale", **info,
              "problems": problems, "ok": not problems}
    result["artifact"] = _scale_write_bench("selftest", result)
    result["history_appended"] = _scale_append_history(info["ramp"])
    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        r, c, g = info["ramp"], info["ceiling"], info["gate"]
        print(f"tpuserve selftest-scale: spike ramp 1->"
              f"{r['max_live']}->{r['final_live']} replicas, "
              f"{r['served']} served / {r['dropped']} dropped, "
              f"{r['scaleup_recompiles']} scale-up recompiles, "
              f"{r['spike_shadows']} spike shadows; ceiling "
              f"deferred={c['deferred_below_ceiling']} "
              f"entries={c['entries']} sheds={c['sheds']}; gate "
              f"rejected={g['rejected']} ({g['reason']})")
        for prob in problems:
            print(f"FAIL: {prob}", file=sys.stderr)
    return 2 if problems else 0


def run_bench_scale(args):
    """Static 1-replica vs SLO-autoscaled under the identical
    traffic_spike script: goodput, peak replicas, compiles. Manual
    drive — deterministic, honest about single-host CPU (the win is
    queueing delay absorbed, not raw FLOPs)."""
    import numpy as np

    from paddle_tpu import telemetry
    from paddle_tpu.resilience import chaos as _chaos
    from paddle_tpu.serving.batcher import RejectedError
    from paddle_tpu.serving.scale import (ScaleController, ScalePlanner,
                                          ScalePolicy)
    telemetry.enable()
    maxlen, buckets = 16, (1, 2, 4)
    cfg, exe, infer, logits, params = _decode_stack(maxlen=maxlen)
    cases = {}
    for label, autoscaled in (("static_1", False),
                              ("autoscaled", True)):
        group, devs = _scale_group(cfg, params, slots=2,
                                   maxlen=maxlen, buckets=buckets,
                                   name=f"bench-{label}",
                                   max_queue=256)
        ctl = None
        if autoscaled:
            ctl = ScaleController(
                group,
                ScalePolicy(["queue_per_replica > 4 -> up",
                             "queue_depth < 1 -> down"],
                            min_replicas=1, max_replicas=4,
                            up_cooldown_s=0.0, down_cooldown_s=0.0,
                            up_dwell=1, down_dwell=2),
                ScalePlanner(group, devices=devs, width=1))
        c0 = group.compile_count
        rng = np.random.RandomState(67)
        reqs = _decode_requests(rng, 24, maxlen, cfg.trg_vocab, 4)
        _chaos.configure("traffic_spike:at=4,x=4,len=8")
        futs, rejected, max_live = [], 0, 1
        probs = []
        t0 = time.monotonic()
        try:
            for src, n, mn in reqs:
                try:
                    futs.append(group.submit(src, src_len=n,
                                             max_new_tokens=mn))
                except RejectedError:
                    rejected += 1
                if ctl is not None:
                    ctl.tick()
                    max_live = max(max_live, len(group.replicas))
        finally:
            _chaos.reset()
        results = _pump_group(group, futs, probs, label, budget=4000)
        wall = time.monotonic() - t0
        tokens = sum(len(r.tokens) for r in results.values())
        case = {"replicas_peak": max_live,
                "served": len(results), "rejected": rejected,
                "dropped": len(probs),
                "compile_count": group.compile_count,
                "extra_compiles": group.compile_count - c0,
                "wall_s": round(wall, 3),
                "goodput_tokens_per_s": round(
                    tokens / max(wall, 1e-6), 1)}
        if ctl is not None:
            case["decisions"] = dict(ctl.decisions)
            ctl.stop()
        group.stop()
        cases[label] = case
        if not args.as_json:
            print(f"  {label:<12} {case['goodput_tokens_per_s']:>8} "
                  f"tok/s  peak {case['replicas_peak']} replicas  "
                  f"{case['extra_compiles']} extra compiles  "
                  f"{case['served']} served")
    result = {"mode": "bench-scale", "model": "transformer-tiny",
              "maxlen": maxlen,
              "fault": "traffic_spike:at=4,x=4,len=8",
              "cases": cases}
    result["artifact"] = _scale_write_bench("bench", result)
    if args.as_json:
        print(json.dumps(result))
    return 0


# ------------------------------------------------------------------ serve
def run_serve(args):
    from paddle_tpu import telemetry
    telemetry.enable()      # /metrics should always have data
    server, frontend = _build_server(args, args.model_dir, args.name)
    engine, version = server.registry.get(args.name)
    print(f"tpuserve: serving {args.name!r} v{version} from "
          f"{args.model_dir} at {frontend.url} "
          f"({engine.signature_count()} signatures warm, buckets "
          f"{server.config.batch.buckets})")
    try:
        frontend.serve_forever()
    except KeyboardInterrupt:
        print("draining...")
    finally:
        server.shutdown()
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        description="dynamic-batching model server over a "
                    "save_inference_model dir")
    p.add_argument("model_dir", nargs="?",
                   help="save_inference_model directory (not needed "
                        "with --selftest)")
    p.add_argument("--name", default="default",
                   help="model name in the /v1/models/<name> route")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8500,
                   help="0 picks an ephemeral port")
    p.add_argument("--buckets", default=None,
                   help="comma-separated batch buckets, e.g. 1,8,32 "
                        "(default: powers of two up to max batch)")
    p.add_argument("--max-batch-size", type=int, default=32)
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline")
    p.add_argument("--platform", default="cpu",
                   help="JAX_PLATFORMS to force ('env' keeps the "
                        "environment's value)")
    p.add_argument("--bench", action="store_true",
                   help="closed-loop load generator; implies no "
                        "serve-forever")
    p.add_argument("--duration", type=float, default=5.0,
                   help="--bench wall-clock seconds")
    p.add_argument("--concurrency", type=int, default=8,
                   help="--bench closed-loop client threads")
    p.add_argument("--selftest", action="store_true",
                   help="CI gate: serve mnist, mixed-shape concurrent "
                        "load, exit non-zero on compile explosion / "
                        "result mismatch / unbounded overload "
                        "(includes the decode leg)")
    p.add_argument("--selftest-decode", action="store_true",
                   dest="selftest_decode",
                   help="just the tpudecode CI gate: greedy_decode "
                        "parity under staggered arrivals, pinned "
                        "executable count, fast overload shed")
    p.add_argument("--bench-decode", action="store_true",
                   dest="bench_decode",
                   help="continuous decode vs the fixed-batch "
                        "greedy_decode path at ~10x overload; writes "
                        "BENCH_decode.json")
    p.add_argument("--selftest-farm", action="store_true",
                   dest="selftest_farm",
                   help="the tpufarm CI gate: replica-group parity + "
                        "compile pin, int8 KV parity bound, one-"
                        "replica-down chaos with zero drops, rolling "
                        "update serving both versions")
    p.add_argument("--bench-farm", action="store_true",
                   dest="bench_farm",
                   help="replica-group bench across 1 vs 2 replicas, "
                        "fp32 vs int8 KV, pooled vs disaggregated "
                        "prefill; writes BENCH_decode2.json")
    p.add_argument("--selftest-guard", action="store_true",
                   dest="selftest_guard",
                   help="the tpuguard CI gate: hedging cuts p99 "
                        "under replica_slow at token parity, a "
                        "flapping replica is ejected/probed/"
                        "re-admitted with zero drops, request_poison "
                        "fails alone, brownout sheds only the lowest "
                        "QoS class and recovers, the retry budget "
                        "caps resubmissions with a typed error")
    p.add_argument("--bench-guard", action="store_true",
                   dest="bench_guard",
                   help="p50/p99 with vs without hedging while "
                        "replica_slow throttles 1 of 2 replicas; "
                        "writes BENCH_guard.json and appends to the "
                        "bench history spine")
    p.add_argument("--selftest-scale", action="store_true",
                   dest="selftest_scale",
                   help="the tpuscale CI gate: a traffic_spike ramp "
                        "must scale 1->N->1 with zero drops and zero "
                        "scale-up recompiles, brownout must shed "
                        "ONLY at the device ceiling (deferred while "
                        "a free slice exists), and an over-cap grow "
                        "must be verify-rejected; writes "
                        "BENCH_autoscale.json + history records")
    p.add_argument("--bench-scale", action="store_true",
                   dest="bench_scale",
                   help="static 1-replica vs SLO-autoscaled group "
                        "under the same traffic_spike script; merges "
                        "into BENCH_autoscale.json")
    p.add_argument("--slots", type=int, default=8,
                   help="--bench-decode slot-pool size")
    p.add_argument("--decode-max-len", type=int, default=32,
                   dest="decode_max_len",
                   help="--bench-decode sequence/cache length")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one machine-readable JSON line")
    args = p.parse_args(argv)

    if args.platform != "env":
        os.environ["JAX_PLATFORMS"] = args.platform
    if args.selftest_farm or args.bench_farm or args.selftest_guard \
            or args.bench_guard or args.selftest_scale \
            or args.bench_scale:
        # the farm slices real devices: give the CPU backend 8
        # virtual ones (must land before jax is first imported)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = \
                (flags + " --xla_force_host_platform_device_count=8") \
                .strip()
    if args.selftest:
        return run_selftest(args)
    if args.selftest_decode:
        return run_selftest_decode(args)
    if args.bench_decode:
        return run_bench_decode(args)
    if args.selftest_farm:
        return run_selftest_farm(args)
    if args.bench_farm:
        return run_bench_farm(args)
    if args.selftest_guard:
        return run_selftest_guard(args)
    if args.bench_guard:
        return run_bench_guard(args)
    if args.selftest_scale:
        return run_selftest_scale(args)
    if args.bench_scale:
        return run_bench_scale(args)
    if not args.model_dir:
        p.error("model_dir is required unless --selftest / "
                "--selftest-decode / --bench-decode / "
                "--selftest-farm / --bench-farm / "
                "--selftest-guard / --bench-guard / "
                "--selftest-scale / --bench-scale")
    if args.bench:
        return run_bench(args)
    return run_serve(args)


if __name__ == "__main__":
    sys.exit(main())
