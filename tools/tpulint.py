#!/usr/bin/env python
"""tpulint — the unified static-analysis gate: proglint + meshlint.

One command, one exit code, for everything the static verifiers can
prove about this repo before anything traces or compiles:

  1. proglint over every benchmark model Program (tools/proglint.py —
     use-before-def, unknown ops, dead code, shape/dtype abstract
     interpretation incl. control-flow sub-blocks, WAW hazards,
     recompile hazards);
  2. meshlint over the sharded-execution configs: the classified red
     multichip test configs (each must classify to a named pass with a
     both-API capability verdict), the green parallel control set
     (must produce ZERO errors — the false-positive pin), the
     gradsync / sparse policy grammars, and the serving FarmConfig
     shapes;
  3. the LINT_multichip.json baseline: the committed classification of
     the 18 red multichip tests must match what the passes derive
     today (drift = the capability table and reality disagree = fail).

Exit status is non-zero when any error-severity diagnostic fires (or
any warning with --strict) — a CI gate, like proglint.

Examples:
  python tools/tpulint.py                      # the whole gate
  python tools/tpulint.py --json               # machine-readable
  python tools/tpulint.py --write-baseline     # refresh LINT_multichip.json
  python tools/tpulint.py --selftest           # fast smoke (tier-1)
"""
import argparse
import json
import os
import sys

# static analysis never needs an accelerator
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tools"))

BASELINE = os.path.join(_REPO, "LINT_multichip.json")

# policy grammar strings the repo's docs/benchmarks advertise — each
# must parse (a grammar regression breaks users' env vars silently)
GRAMMAR_FIXTURES = {
    "grad_sync": ["fp32", "bf16", "int8", "int8:bucket_mb=1",
                  "bf16:bucket_kb=256,block=128",
                  "int8:overlap=0,ef=1", "fp32:reduce=sum"],
    "sparse": ["shard", "shard:stale=2", "shard:stale=4,cap=1024",
               "shard:kernel=0", "1", "on"],
}


def _meshlint():
    from paddle_tpu.analysis import meshlint
    return meshlint


def lint_models(names=None, quiet=False):
    """Section 1: proglint over the benchmark models."""
    import proglint
    out = {}
    for name in names or proglint.ALL_MODELS:
        diags, n_ops = proglint.lint_model(name)
        if quiet:
            diags = [d for d in diags if d.severity != "info"]
        out[name] = {"ops": n_ops,
                     "diagnostics": [d.to_dict() for d in diags]}
    return out


def lint_mesh_configs(quiet=False):
    """Section 2: meshlint — red classification, green control set,
    policy grammars, farm shapes."""
    from paddle_tpu.analysis.diagnostics import Diagnostic, ERROR
    ml = _meshlint()
    out = {"red": [], "green": {}, "grammars": {}, "farm": {},
           "errors": []}

    for rec in ml.classify_red_tests():
        out["red"].append(rec)
        if not rec["classified"]:
            out["errors"].append(
                f"red config {rec['test']} did not classify: no "
                f"meshlint pass names a capability for it")

    for label, mctx in ml.green_configs():
        diags = ml.run_mesh_passes(mctx)
        if quiet:
            diags = [d for d in diags if d.severity != "info"]
        out["green"][label] = [d.to_dict() for d in diags]
        for d in diags:
            if d.severity == "error":
                out["errors"].append(
                    f"FALSE POSITIVE: green config {label!r} got "
                    f"[{d.pass_name}] {d.message}")

    from paddle_tpu.parallel import gradsync, sparse
    for kind, parse in (("grad_sync", gradsync.parse_policy),
                        ("sparse", sparse.parse_policy)):
        for g in GRAMMAR_FIXTURES[kind]:
            try:
                parse(g)
                out["grammars"][f"{kind}:{g}"] = "ok"
            except Exception as e:
                out["grammars"][f"{kind}:{g}"] = f"FAIL: {e}"
                out["errors"].append(
                    f"{kind} grammar {g!r} no longer parses: {e}")

    from paddle_tpu.serving.farm import FarmConfig
    from paddle_tpu.serving.decode import DecodeEngineConfig
    farm_shapes = {
        "default": FarmConfig(),
        "prefill-disagg": FarmConfig(replicas=2, prefill_devices=1),
        "kv-int8": FarmConfig(engine=DecodeEngineConfig(
            num_slots=8, kv_quant="int8")),
    }
    for label, cfg in farm_shapes.items():
        diags = cfg.verify()
        if quiet:
            diags = [d for d in diags if d.severity != "info"]
        out["farm"][label] = [d.to_dict() for d in diags]
        for d in diags:
            if d.severity == "error":
                out["errors"].append(
                    f"farm shape {label!r}: [{d.pass_name}] "
                    f"{d.message}")
    return out


def check_baseline(red_records):
    """Section 3: the committed LINT_multichip.json must match today's
    derivation (test -> pass/capability). Returns error strings."""
    if not os.path.exists(BASELINE):
        return [f"baseline {BASELINE} missing; run "
                f"tools/tpulint.py --write-baseline and commit it"]
    with open(BASELINE) as f:
        base = json.load(f)
    errs = []
    base_by_test = {r["test"]: r for r in base.get("red_tests", [])}
    now_by_test = {r["test"]: r for r in red_records}
    for test in sorted(set(base_by_test) | set(now_by_test)):
        b, n = base_by_test.get(test), now_by_test.get(test)
        if b is None:
            errs.append(f"red config {test} is new (not in baseline)")
        elif n is None:
            errs.append(f"baseline red config {test} no longer "
                        f"derived")
        elif (b["pass"], b["capability"]) != (n["pass"],
                                              n["capability"]):
            errs.append(
                f"classification drift for {test}: baseline "
                f"{b['pass']}/{b['capability']} vs derived "
                f"{n['pass']}/{n['capability']}")
    return errs


def write_baseline(red_records):
    ml = _meshlint()
    payload = {
        "comment": "Machine-readable classification of the red "
                   "multichip tests: which meshlint pass flags each "
                   "config and the per-API capability verdict. "
                   "Regenerate with tools/tpulint.py --write-baseline "
                   "after changing the capability table or the tests.",
        "api_profiles": list(ml.api_profiles()),
        "mesh_passes": ml.mesh_pass_names(),
        "red_tests": red_records,
    }
    with open(BASELINE, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")
    return payload


def selftest():
    """Fast smoke for tier-1 (tpudoctor pattern: last stdout line is a
    JSON object with an "ok" field). Exercises every pass once with a
    seeded defect and once clean — no model builds, subsecond."""
    ml = _meshlint()
    checks = {}
    # seeded defect: unknown axis + non-divisible dim must both fire
    mesh = ml.MeshSpec({"dp": 4, "tp": 2})
    use = ml.ShardMapUse("selftest", in_specs=[("xx",), ("dp", "tp")],
                         arg_shapes=[(8,), (6, 4)])  # 6 % dp=4 != 0
    diags = ml.run_mesh_passes(ml.MeshLintContext(mesh, uses=[use]),
                               passes=["mesh-spec"])
    errs = [d for d in diags if d.severity == "error"]
    checks["seeded_spec_defect_fires"] = len(errs) >= 2
    # clean config: no errors
    ok_use = ml.ShardMapUse("selftest-ok", in_specs=[("dp",)],
                            arg_shapes=[(8,)])
    diags = ml.run_mesh_passes(ml.MeshLintContext(mesh, uses=[ok_use]))
    checks["clean_config_quiet"] = not any(
        d.severity == "error" for d in diags)
    # every advertised pass is registered
    checks["passes_registered"] = set(ml.mesh_pass_names()) == {
        "mesh-spec", "collective-consistency", "donation-aliasing",
        "device-footprint", "mesh-recompile-hazard",
        "kern-capability"}
    # all red configs classify and the baseline (when present) agrees
    recs = ml.classify_red_tests()
    checks["red_configs_classified"] = (
        len(recs) == 18 and all(r["classified"] for r in recs))
    if os.path.exists(BASELINE):
        checks["baseline_consistent"] = not check_baseline(recs)
    # green control set stays quiet
    checks["green_zero_errors"] = all(
        not any(d.severity == "error" for d in ml.run_mesh_passes(m))
        for _, m in ml.green_configs())
    ok = all(checks.values())
    print(json.dumps({"ok": ok, "checks": checks,
                      "passes": ml.mesh_pass_names()}))
    return 0 if ok else 1


def main(argv=None):
    p = argparse.ArgumentParser(
        description="unified static-analysis gate (proglint + meshlint)")
    p.add_argument("models", nargs="*", default=None,
                   help="benchmark models to proglint (default: all)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--strict", action="store_true",
                   help="warnings also fail the exit status")
    p.add_argument("--quiet", action="store_true",
                   help="suppress info-severity diagnostics")
    p.add_argument("--skip-models", action="store_true",
                   help="meshlint sections only (no model builds)")
    p.add_argument("--write-baseline", action="store_true",
                   help=f"write {os.path.basename(BASELINE)} and exit")
    p.add_argument("--list-passes", action="store_true",
                   help="print proglint + meshlint pass names and exit")
    p.add_argument("--selftest", action="store_true",
                   help="fast smoke; last stdout line is JSON verdict")
    args = p.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.list_passes:
        from paddle_tpu.analysis import pass_names
        ml = _meshlint()
        print("\n".join(pass_names()))
        print("\n".join(ml.mesh_pass_names()))
        return 0

    mesh_report = lint_mesh_configs(quiet=args.quiet)
    if args.write_baseline:
        write_baseline(mesh_report["red"])
        print(f"wrote {BASELINE} ({len(mesh_report['red'])} red "
              f"configs)")
        return 0
    mesh_report["baseline"] = check_baseline(mesh_report["red"])

    model_report = {}
    if not args.skip_models:
        model_report = lint_models(args.models, quiet=args.quiet)

    failed = bool(mesh_report["errors"] or mesh_report["baseline"])
    n_warn_total = 0
    for name, rec in model_report.items():
        sevs = [d["severity"] for d in rec["diagnostics"]]
        n_err, n_warn = sevs.count("error"), sevs.count("warning")
        n_warn_total += n_warn
        if n_err:
            failed = True
        if not args.as_json:
            status = "FAIL" if n_err else ("warn" if n_warn else "ok")
            print(f"proglint {name:<24} {rec['ops']:>4} ops  "
                  f"{n_err} error(s), {n_warn} warning(s)  [{status}]")
    for label, dl in list(mesh_report["green"].items()) \
            + list(mesh_report["farm"].items()):
        n_warn_total += sum(d["severity"] == "warning" for d in dl)
    if args.strict and n_warn_total:
        failed = True

    if not args.as_json:
        n_red = sum(r["classified"] for r in mesh_report["red"])
        print(f"meshlint {n_red}/{len(mesh_report['red'])} red "
              f"multichip configs classified, "
              f"{len(mesh_report['green'])} green configs clean, "
              f"{len(mesh_report['grammars'])} grammars, "
              f"{len(mesh_report['farm'])} farm shapes")
        for e in mesh_report["errors"] + mesh_report["baseline"]:
            print(f"  error: {e}")
    else:
        print(json.dumps({"models": model_report,
                          "meshlint": mesh_report}, indent=1))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
