#!/usr/bin/env python
"""tpumem — the device-memory ledger's CLI.

Four jobs:

  demo        (default) run a tiny training job under the ledger
              (PADDLE_TPU_MEMLEDGER=1), let it fit under the device
              cap, then grow a decode KV cache past it and show the
              OOM doctor's MemoryReport: top allocations by category,
              peak-vs-cap, and the "what grew since the last fit"
              diff phrased in the shared ckey vocabulary.
  snapshot    pretty-print a live memory snapshot from a farm
              (`GET /v1/memory` URL) or a telemetry-dir memory.json.
  watch       re-poll a /v1/memory URL and print one line per sample.
  postmortem  pretty-print a flight-recorder dump that carries a
              memory report (reason memory_oom / memory_over_cap).
  --selftest  CI gate (pattern of tools/tpudoctor.py --selftest):
              the demo with assertions — the over-cap report names
              the correct top category with a ckey-vocab growth diff
              and round-trips through the flight recorder; ledger KV
              bytes match the engine's analytic kv_cache_bytes for
              fp32 AND int8; the measured runtime footprint
              reconciles against meshlint's static floor (and an
              injected mismatch trips the drift WARNING);
              ScalePlanner rejects a grow that measured bytes rule
              out (reason "measured") even though the static floor
              fits; and with PADDLE_TPU_MEMLEDGER unset a subprocess
              never imports the ledger module. One JSON verdict line
              with --json; exit 2 on any problem.

Examples:
  python tools/tpumem.py                          # demo
  python tools/tpumem.py snapshot http://HOST:PORT/v1/memory
  python tools/tpumem.py watch http://HOST:PORT/v1/memory -n 10
  python tools/tpumem.py postmortem flight_recorder/flight_123.json
  python tools/tpumem.py --selftest --json
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


# -------------------------------------------------------------- rendering

def _fmt_bytes(n):
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.2f}{unit}")
        n /= 1024
    return f"{n:.2f}GiB"


def format_snapshot(payload):
    """Human rendering of a /v1/memory (or memory.json) payload."""
    if not payload.get("enabled", True):
        lines = ["memory ledger: disabled (PADDLE_TPU_MEMLEDGER unset)"]
        dev = payload.get("device") or {}
        for k, v in sorted(dev.items()):
            lines.append(f"  {k}: {_fmt_bytes(v)}")
        return "\n".join(lines)
    cap = payload.get("cap_bytes")
    lines = [
        f"memory ledger: {_fmt_bytes(payload.get('total_bytes', 0))} "
        f"live, {_fmt_bytes(payload.get('peak_bytes', 0))} peak / "
        f"{'cap ' + _fmt_bytes(cap) if cap else 'uncapped'} "
        f"({payload.get('steps', 0)} step samples)"]
    cats = payload.get("categories") or {}
    for c, b in sorted(cats.items(), key=lambda kv: -kv[1]):
        lines.append(f"  {c:<13} {_fmt_bytes(b)}")
    owners = payload.get("owners") or []
    if owners:
        lines.append("  top owners:")
        for o in owners[:8]:
            lines.append(f"    {o['category']}/{o['owner']:<20} "
                         f"{_fmt_bytes(o['bytes'])}")
    rp = payload.get("replica_peaks") or {}
    if rp:
        peaks = " ".join(f"{k}={_fmt_bytes(v)}"
                         for k, v in sorted(rp.items()))
        lines.append(f"  replica peaks: {peaks}")
    if payload.get("last_report"):
        lr = payload["last_report"]
        lines.append(f"  last report: {lr.get('reason')} "
                     f"(top {lr.get('top_category')})")
    return "\n".join(lines)


def _fetch(src):
    if src.startswith(("http://", "https://")):
        from urllib.request import urlopen
        with urlopen(src, timeout=10) as r:
            return json.loads(r.read().decode())
    with open(src) as f:
        return json.load(f)


def cmd_snapshot(src, as_json):
    payload = _fetch(src)
    if as_json:
        print(json.dumps(payload, default=str))
    else:
        print(format_snapshot(payload))
    return 0


def cmd_watch(src, interval, iterations):
    i = 0
    while iterations is None or i < iterations:
        try:
            p = _fetch(src)
        except OSError as e:
            print(f"tpumem watch: {e}", file=sys.stderr)
            return 2
        cats = p.get("categories") or {}
        top = ",".join(f"{c}={_fmt_bytes(b)}" for c, b in sorted(
            cats.items(), key=lambda kv: -kv[1])[:3])
        cap = p.get("cap_bytes")
        print(f"[{time.strftime('%H:%M:%S')}] "
              f"live {_fmt_bytes(p.get('total_bytes', 0)):>10} "
              f"peak {_fmt_bytes(p.get('peak_bytes', 0)):>10} "
              f"{('cap ' + _fmt_bytes(cap)) if cap else 'uncapped':>12} "
              f" {top}")
        i += 1
        if iterations is None or i < iterations:
            time.sleep(interval)
    return 0


def cmd_postmortem(path):
    with open(path) as f:
        payload = json.load(f)
    rep = payload.get("report")
    if rep and rep.get("kind") == "memory":
        from paddle_tpu.telemetry.memledger import MemoryReport
        r = MemoryReport(
            rep.get("reason", "?"), error=rep.get("error"),
            context=rep.get("context"), cap_bytes=rep.get("cap_bytes"),
            total_bytes=rep.get("total_bytes", 0),
            peak_bytes=rep.get("peak_bytes", 0),
            categories=rep.get("categories"), top=rep.get("top"),
            growth=rep.get("growth"), hints=rep.get("hints"),
            device=rep.get("device"), timeline=rep.get("timeline"))
        print(f"flight dump {payload.get('reason')} "
              f"(pid {payload.get('pid')})")
        print(r.format())
        tl = rep.get("timeline") or []
        if tl:
            print(f"  timeline (last {min(len(tl), 8)} of {len(tl)}):")
            for t in tl[-8:]:
                print(f"    step {t.get('step'):>6}  "
                      f"{_fmt_bytes(t.get('total', 0))}")
    else:
        print(json.dumps(payload, indent=2, default=str))
    return 0


# ------------------------------------------------------------------- demo

def _mlp_stack():
    """Tiny FC/Momentum training program + a feed, the demo workload."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import layers
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            x = layers.data("x", shape=[16])
            label = layers.data("label", shape=[1], dtype="int64")
            h = layers.fc(x, size=32, act="relu")
            pred = layers.fc(h, size=8, act="softmax")
            loss = layers.mean(
                layers.cross_entropy(input=pred, label=label))
            pt.optimizer.Momentum(0.1, momentum=0.9).minimize(loss)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 16).astype("float32"),
            "label": rng.randint(0, 8, (8, 1)).astype("int64")}
    return main, exe, loss, feed


def _decode_engine(kv_quant=None, num_slots=2, maxlen=12):
    """Tiny DecodeEngine (no warmup — init_state is the creation site
    under test, compiling nothing)."""
    import paddle_tpu as pt
    from paddle_tpu.core import framework as fw
    from paddle_tpu.models import transformer as tfm
    from paddle_tpu.serving.decode import DecodeEngine, DecodeEngineConfig
    cfg = tfm.TransformerConfig(
        src_vocab=32, trg_vocab=32, max_len=maxlen, d_model=16,
        d_inner=32, n_head=2, n_layer=2, dropout=0.0)
    infer, start = fw.Program(), fw.Program()
    with pt.program_guard(infer, start):
        with pt.unique_name.guard():
            tfm.build_infer_program(cfg, maxlen=maxlen)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(start)
    import numpy as np
    scope = pt.global_scope()
    params = {v.name: np.asarray(scope.get(v.name))
              for v in infer.persistable_vars()}
    return DecodeEngine(cfg, params, DecodeEngineConfig(
        num_slots=num_slots, max_len=maxlen, prefill_buckets=(1, 2),
        kv_quant=kv_quant))


def run_demo(selftest=False):
    problems = []
    info = {}

    def check(cond, what):
        if not cond:
            problems.append(what)
        return cond

    from paddle_tpu import telemetry as tm
    tm.memledger_enable()
    tm.enable()
    from paddle_tpu.telemetry import memledger as ml
    from paddle_tpu.diagnostics import recorder as flight
    ml.reset()
    os.environ.pop("PADDLE_TPU_DEVICE_MEM_CAP", None)
    flight_dir = tempfile.mkdtemp(prefix="tpumem_flight_")
    flight.enable(out_dir=flight_dir, install_hooks=False)

    # ---- act 1: train a few steps, uncapped — the ledger marks fits
    main, exe, loss, feed = _mlp_stack()
    for _ in range(3):
        exe.run(main, feed=feed, fetch_list=[loss])
    snap = ml.snapshot_report()
    fit_total = snap["total_bytes"]
    if not selftest:
        print("after 3 training steps (uncapped):")
        print(format_snapshot(snap))
    check(snap["categories"].get("params", 0) > 0,
          "no params bytes attributed after training steps")
    check(snap["categories"].get("optimizer", 0) > 0,
          "no optimizer slot bytes attributed (Momentum has velocity)")
    check(snap["categories"].get("feed", 0) > 0,
          "no feed bytes attributed")

    # ---- act 2: static-vs-runtime reconciliation on the same model
    # (before any serving state exists — the static floor prices
    # params + optimizer slots, so the measured side must too)
    from paddle_tpu.analysis import meshlint as mlint
    from paddle_tpu.analysis.meshlint.footprint import member_footprint
    fp = member_footprint(mlint.MeshLintContext(
        mlint.MeshSpec({"dp": 1}), program=main))
    rec = ml.reconcile(fp, tolerance=0.25, label="tpumem demo MLP")
    check(rec["ok"],
          f"runtime footprint {rec['measured_bytes']} drifted past "
          f"tolerance from static floor {rec['static_bytes']} "
          f"(x{rec['ratio']:.2f})")
    info["reconcile_ratio"] = round(rec["ratio"], 4)
    if not selftest:
        print(f"\nstatic floor {_fmt_bytes(rec['static_bytes'])} vs "
              f"measured peak {_fmt_bytes(rec['measured_bytes'])} "
              f"(x{rec['ratio']:.2f}) — "
              f"{'reconciled' if rec['ok'] else 'DRIFT'}")
    # an injected mismatch must trip the drift WARNING + alarm gauge
    import jax.numpy as jnp
    bogus = jnp.zeros(max(1, fp["total"] * 3 // 4), jnp.uint8)
    ml.register("params", "drift_probe", bogus)
    ml.on_step(context={"site": "tpumem.selftest"})
    bad = ml.reconcile(fp, tolerance=0.25, label="injected mismatch")
    check(not bad["ok"], "injected 1.75x mismatch not flagged")
    check(bad["diagnostic"] is not None
          and bad["diagnostic"].severity == "warning"
          and bad["diagnostic"].pass_name == "memledger-drift",
          "drift beyond tolerance produced no WARNING diagnostic")
    from paddle_tpu.telemetry import registry as treg
    check(treg.gauge("memledger.static_drift_alarm").value == 1.0,
          "memledger.static_drift_alarm gauge did not fire")
    del bogus

    # ---- act 3: KV parity, fp32 (the farm gauge's analytic number vs
    # what the creation site actually registered)
    eng_f32 = _decode_engine(kv_quant=None)
    before = ml.snapshot_report()["categories"].get("kv_cache", 0)
    state_f32 = eng_f32.init_state()         # keep the arrays alive
    after = ml.snapshot_report()["categories"].get("kv_cache", 0)
    check(after - before == eng_f32.kv_cache_bytes,
          f"kv_quant=None: ledger measured {after - before} bytes, "
          f"engine analytic kv_cache_bytes={eng_f32.kv_cache_bytes}")
    f32 = eng_f32.kv_cache_bytes
    i8_eng = _decode_engine(kv_quant="int8")  # params register at ctor
    i8 = i8_eng.kv_cache_bytes
    check(0.2 < i8 / f32 < 0.8,
          f"int8 KV cache not smaller than fp32 ({i8} vs {f32})")
    info["kv_fp32_bytes"] = f32
    info["kv_int8_bytes"] = i8

    # ---- act 4: one uncapped step marks the fit with everything but
    # the int8 engine's KV state; cap the device halfway into that
    # growth — creating the cache then stepping breaches, and the OOM
    # doctor's diff names the KV cache in ckey vocabulary
    exe.run(main, feed=feed, fetch_list=[loss])
    fit_total = ml.snapshot_report()["total_bytes"]
    cap_bytes = fit_total + i8 // 2
    os.environ["PADDLE_TPU_DEVICE_MEM_CAP"] = \
        str(cap_bytes / (1 << 20))
    before = ml.snapshot_report()["categories"].get("kv_cache", 0)
    state_i8 = i8_eng.init_state()
    after = ml.snapshot_report()["categories"].get("kv_cache", 0)
    check(after - before == i8,
          f"kv_quant=int8: ledger measured {after - before} bytes, "
          f"engine analytic kv_cache_bytes={i8}")
    exe.run(main, feed=feed, fetch_list=[loss])
    rep = ml.last_report()
    if check(rep is not None, "no MemoryReport after the over-cap "
                              "step"):
        check(rep.reason == "over_cap",
              f"report reason {rep.reason!r}, wanted 'over_cap'")
        check(rep.top_growth_category == "kv_cache",
              f"top growth category {rep.top_growth_category!r}, the "
              f"KV caches grew — wanted 'kv_cache'")
        phrases = [g["phrase"] for g in rep.growth]
        check(any("engine" in p for p in phrases),
              f"growth diff not phrased in ckey vocab (phrases: "
              f"{phrases})")
        check(any("kv_quant" in h or "int8" in h for h in rep.hints),
              f"no kv_quant fix hint in {rep.hints}")
        check(rep.peak_bytes > cap_bytes,
              "reported peak does not exceed the cap")
        info["report_top_growth"] = rep.top_growth_category
        if not selftest:
            print("\ncap set between the fit and the KV growth — the "
                  "over-cap doctor fired:")
            print(rep.format())
    dumps = [f for f in os.listdir(flight_dir) if f.endswith(".json")]
    if check(bool(dumps), "flight recorder wrote no memory dump"):
        with open(os.path.join(flight_dir, sorted(dumps)[-1])) as f:
            payload = json.load(f)
        check(payload.get("reason") == "memory_over_cap",
              f"dump reason {payload.get('reason')!r}")
        check((payload.get("report") or {}).get("kind") == "memory",
              "dump carries no typed memory report")
        # per-step HBM watermark rides the flight ring (satellite)
        recs = payload.get("records") or []
        check(any("hbm" in r for r in recs),
              "flight records carry no per-step hbm watermark")
    os.environ.pop("PADDLE_TPU_DEVICE_MEM_CAP", None)
    flight.disable()

    # ---- act 5: the measured gate — ScalePlanner rejects a grow the
    # runtime ledger rules out even though the static floor fits
    from paddle_tpu.serving.scale.planner import (ScalePlanner,
                                                  ScalePlanRejected)

    class _Slice(list):
        pass

    class _StubGroup:
        """Allocator-only surface: grow is rejected before spawn."""
        class config:
            devices = [0, 1, 2, 3]
        prefill_devices = ()
        replicas = ()
        model_cfg = None

    pl = ScalePlanner(_StubGroup(), devices=[0, 1, 2, 3], width=1,
                      verify=False,
                      measured_bytes=lambda: 2 * (1 << 20))
    os.environ["PADDLE_TPU_DEVICE_MEM_CAP"] = "1"    # 1 MiB cap
    check(pl.at_ceiling(), "measured 2MiB > 1MiB cap but at_ceiling "
                           "is False")
    try:
        pl.grow(1)
        problems.append("grow succeeded despite measured overrun")
    except ScalePlanRejected as e:
        check(e.reason == "measured",
              f"rejection reason {e.reason!r}, wanted 'measured'")
        check("measured per-replica peak" in str(e),
              f"rejection message unhelpful: {e}")
    pl2 = ScalePlanner(_StubGroup(), devices=[0, 1, 2, 3], width=1,
                       verify=False,
                       measured_bytes=lambda: 64 * 1024)
    check(not pl2.at_ceiling(),
          "64KiB measured under a 1MiB cap reported at_ceiling")
    os.environ.pop("PADDLE_TPU_DEVICE_MEM_CAP", None)
    info["planner_measured_gate"] = "rejected"
    if not selftest:
        print("\nScalePlanner: grow rejected (reason 'measured') — "
              "runtime bytes overruled the static floor")

    # ---- act 6: off-path purity — unset, the ledger module is never
    # imported (subprocess; the bench-contract test pins fetch bytes)
    if selftest:
        code = (
            "import os, sys\n"
            "os.environ.pop('PADDLE_TPU_MEMLEDGER', None)\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import paddle_tpu as pt\n"
            "from paddle_tpu import telemetry as tm\n"
            "assert tm.memledger_enabled() is False\n"
            "import numpy as np\n"
            "from paddle_tpu import layers\n"
            "main, st = pt.Program(), pt.Program()\n"
            "with pt.program_guard(main, st):\n"
            "    with pt.unique_name.guard():\n"
            "        x = layers.data('x', shape=[4])\n"
            "        y = layers.fc(x, size=2)\n"
            "exe = pt.Executor(pt.CPUPlace())\n"
            "exe.run(st)\n"
            "exe.run(main, feed={'x': np.ones((2, 4), 'float32')},\n"
            "        fetch_list=[y])\n"
            "assert 'paddle_tpu.telemetry.memledger' not in "
            "sys.modules, 'memledger imported on the off path'\n"
            "print('PURE')\n")
        env = dict(os.environ)
        env.pop("PADDLE_TPU_MEMLEDGER", None)
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=240,
                           cwd=_REPO)
        check(r.returncode == 0 and "PURE" in r.stdout,
              f"off-path purity subprocess failed: "
              f"{r.stdout[-500:]} {r.stderr[-500:]}")

    tm.disable()
    tm.memledger_disable()
    ml.reset()
    return problems, info


# ------------------------------------------------------------------- main

def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("command", nargs="?", default="demo",
                   choices=["demo", "snapshot", "watch", "postmortem"])
    p.add_argument("path", nargs="?", default=None,
                   help="snapshot/watch: /v1/memory URL or memory.json "
                        "path; postmortem: flight dump path")
    p.add_argument("--selftest", action="store_true",
                   help="run the CI gate assertions")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one machine-readable JSON verdict line")
    p.add_argument("-n", "--iterations", type=int, default=None,
                   help="watch: number of samples (default: forever)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="watch: seconds between samples")
    p.add_argument("--platform", default="cpu",
                   help="JAX_PLATFORMS to force ('env' keeps the "
                        "environment's; default cpu so the CLI never "
                        "hangs on a down relay)")
    args = p.parse_args(argv)

    if args.command in ("snapshot", "watch", "postmortem") \
            and not args.path:
        p.error(f"{args.command} needs a URL or path")
    if args.command == "postmortem":
        return cmd_postmortem(args.path)
    if args.command == "snapshot":
        return cmd_snapshot(args.path, args.as_json)
    if args.command == "watch":
        return cmd_watch(args.path, args.interval, args.iterations)

    if args.platform != "env":
        os.environ["JAX_PLATFORMS"] = args.platform
    os.environ["PADDLE_TPU_MEMLEDGER"] = "1"

    problems, info = run_demo(selftest=args.selftest)
    result = {"ok": not problems, "problems": problems}
    result.update(info)
    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        if problems:
            for prob in problems:
                print(f"PROBLEM: {prob}", file=sys.stderr)
        else:
            print("\ntpumem: all checks passed "
                  f"(kv fp32 {_fmt_bytes(info['kv_fp32_bytes'])}, "
                  f"int8 {_fmt_bytes(info['kv_int8_bytes'])}, "
                  f"reconcile x{info['reconcile_ratio']})")
    return 2 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
