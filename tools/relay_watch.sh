#!/bin/bash
# Background watcher: try a relay window every INTERVAL seconds, logging
# to /tmp/relay_watch.log. Start once per round:
#   nohup bash tools/relay_watch.sh > /dev/null 2>&1 &
INTERVAL=${INTERVAL:-1200}
while true; do
  bash /root/repo/tools/relay_window.sh >> /tmp/relay_watch.log 2>&1
  sleep "$INTERVAL"
done
