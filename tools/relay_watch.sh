#!/bin/bash
# Background watcher: try a relay window every INTERVAL seconds, logging
# to /tmp/relay_watch.log. Start once per round:
#   nohup bash tools/relay_watch.sh > /dev/null 2>&1 &
# flock single-instance guard: stacked watchers (or a concurrent manual
# relay_window.sh) would otherwise race the shared stage files and run
# concurrent benches against the one chip.
INTERVAL=${INTERVAL:-1200}
exec 9>/tmp/relay_watch.lock
if ! flock -n 9; then
  echo "relay_watch already running; exiting" >&2
  exit 0
fi
while true; do
  bash /root/repo/tools/relay_window.sh >> /tmp/relay_watch.log 2>&1
  sleep "$INTERVAL"
done
