#!/bin/bash
# One relay window: probe; if the chip answers, immediately capture a
# full bench run (short budget fits this window) + stamp the output.
cd /root/repo
P=$(python -c "import bench; print(bench._probe_tpu(timeout=100) or '')")
if [ -z "$P" ]; then echo "RELAY DOWN $(date +%H:%M:%S)"; exit 0; fi
echo "RELAY UP ($P) $(date +%H:%M:%S) — capturing bench"
BENCH_TOTAL_BUDGET_S=400 timeout 430 python bench.py 2>/tmp/relay_bench.err | tee /tmp/relay_bench.jsonl | tail -1
