#!/bin/bash
# One relay window per invocation: probe; if the chip answers, run the
# next uncaptured measurement stage (bench -> mfu A/B -> flash A/B).
cd /root/repo
P=$(python -c "
import bench
r = bench._probe_tpu(timeout=100)
ok = r['outcome'] == 'ok' and r.get('platform') in ('tpu', 'axon')
print(r['platform'] if ok else '')")
if [ -z "$P" ]; then echo "RELAY DOWN $(date +%H:%M:%S)"; exit 0; fi
echo "RELAY UP ($P) $(date +%H:%M:%S)"
if [ ! -s /tmp/relay_bench.jsonl ]; then
  echo "— capturing bench"
  BENCH_TOTAL_BUDGET_S=400 timeout 430 python bench.py \
    2>/tmp/relay_bench.err | tee /tmp/relay_bench.jsonl | tail -1
elif [ ! -s /tmp/relay_mfu_fused.out ]; then
  echo "— capturing mfu_probe (fused)"
  timeout 430 python tools/mfu_probe.py --steps 10 \
    >/tmp/relay_mfu_fused.out 2>/tmp/relay_mfu_fused.err
  tail -5 /tmp/relay_mfu_fused.out
elif [ ! -s /tmp/relay_mfu_unfused.out ]; then
  echo "— capturing mfu_probe (unfused A/B)"
  timeout 430 python tools/mfu_probe.py --steps 10 --no-fused-qkv \
    >/tmp/relay_mfu_unfused.out 2>/tmp/relay_mfu_unfused.err
  tail -5 /tmp/relay_mfu_unfused.out
elif [ ! -s /tmp/relay_mfu_bf16sm.out ]; then
  echo "— capturing mfu_probe (bf16 flash softmax A/B)"
  timeout 430 python tools/mfu_probe.py --steps 10 --flash-bf16-softmax \
    >/tmp/relay_mfu_bf16sm.out 2>/tmp/relay_mfu_bf16sm.err
  tail -5 /tmp/relay_mfu_bf16sm.out
else
  echo "— all stages captured; rerunning bench to warm caches"
  BENCH_TOTAL_BUDGET_S=400 timeout 430 python bench.py \
    2>/dev/null | tail -1
fi
