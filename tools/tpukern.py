#!/usr/bin/env python
"""tpukern — the kernel-registry CLI (ops/kern).

Subcommands:

  list        registered kernels: adapter keys, tolerance, tune space,
              one-line note. Loads no backend.
  probe       run each kernel's STATIC capability probe against its
              example shapes (jax.ShapeDtypeStruct — no data touches a
              device) and again with interpret=True; shows what the
              dispatch seam would accept where.
  tune        autotune block sizes for one/all kernels on the live
              backend; entries land in $PADDLE_TPU_KERN_CACHE and
              --emit-baseline writes/merges the committed
              KERN_TUNED.json warm-start (--tpu-defaults appends the
              docsweep v5e entries for the canonical bench shapes).
  bench       A/B each kernel vs its jnp reference composition (median
              jit wall time + max|Δ|); `--flash-ab` reproduces the
              retired tools/flash_ab.py measurement — causal fwd+bwd
              flash attention with the in-kernel probability exp in f32
              (exact algorithm) vs bf16 (VPU-pressure escape), wall
              time, attn-MFU, and output/grad deltas per seqlen.
  --selftest  CI gate (pattern of tools/tpudoctor.py --selftest): every
              registered kernel probes its example statically, passes
              its parity gate in interpret mode, and the autotune cache
              round-trips (publish -> reload -> torn entry rejected).
              One JSON verdict line with --json; exit 2 on any problem.

Examples:
  python tools/tpukern.py list
  python tools/tpukern.py probe
  python tools/tpukern.py tune --mode interpret --emit-baseline KERN_TUNED.json
  python tools/tpukern.py bench --kernels int8_quant,layer_norm
  python tools/tpukern.py bench --flash-ab --seqlens 8192,32768
  python tools/tpukern.py --selftest --json
"""
import argparse
import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _load_registry():
    """Import the registry (and its kernel registrations) lazily so
    `--platform` lands in the environment first."""
    from paddle_tpu.ops import kern
    return kern


def _pick_specs(kern, names_csv):
    names = kern.names()
    if names_csv:
        want = [n.strip() for n in names_csv.split(",") if n.strip()]
        missing = [n for n in want if n not in names]
        if missing:
            raise SystemExit(f"unknown kernel(s) {missing}; "
                             f"registered: {names}")
        names = want
    return [kern.get(n) for n in names]


def _shape_structs(args):
    """Data-free probe operands: arrays become ShapeDtypeStructs,
    everything else passes through."""
    import jax
    out = []
    for a in args:
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            out.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
        else:
            out.append(a)
    return out


def _example(spec, seed=0):
    import numpy as np
    if spec.example is None:
        return None
    return spec.example(np.random.RandomState(seed))


# ------------------------------------------------------------------ list

def cmd_list(args):
    kern = _load_registry()
    rows = []
    for spec in kern.specs():
        ex = _example(spec)
        tunable = "yes" if spec.signature is not None else "no"
        ncand = len(spec.tune_space(*ex[0], **ex[1])) if (
            ex and spec.signature is not None) else 0
        rows.append((spec.name, ",".join(spec.op_types),
                     f"rtol={spec.tol[0]:g},atol={spec.tol[1]:g}",
                     f"{tunable}({ncand})" if ncand else tunable,
                     spec.note))
    widths = [max(len(r[i]) for r in rows) for i in range(4)]
    hdr = ("kernel", "adapter keys", "parity tol", "tunable", "note")
    widths = [max(w, len(h)) for w, h in zip(widths, hdr[:4])]
    print("  ".join(h.ljust(w) for h, w in zip(hdr[:4], widths))
          + "  " + hdr[4])
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r[:4], widths))
              + "  " + r[4])
    return 0


# ----------------------------------------------------------------- probe

def cmd_probe(args):
    kern = _load_registry()
    bad = 0
    for spec in _pick_specs(kern, args.kernels):
        ex = _example(spec)
        if ex is None:
            print(f"{spec.name:<22} (no example registered)")
            continue
        a, kw = ex
        structs = _shape_structs(a)
        static = bool(spec.probe(*structs, **kw))
        interp = bool(spec.probe(*structs, interpret=True, **kw))
        mark = "ok" if interp else "REJECT"
        if not interp:
            bad += 1
        print(f"{spec.name:<22} static={'accept' if static else 'reject'}"
              f"  interpret={'accept' if interp else 'reject'}  [{mark}]")
    return 1 if bad else 0


# ------------------------------------------------------------------ tune

# Hardware warm-start entries for the canonical bench shapes, from the
# flash-attention docstring block sweep on v5e (8x128-lane tiles; see
# ops/pallas/flash_attention.py "block-size sweep" note). These are the
# shapes bench.py's flash stage and the serving decode tier actually
# run; `tpukern tune` on a real chip replaces them with measured
# entries under the same keys.
_TPU_DEFAULTS = [
    {"kernel": "flash_attention", "sig": [1, 8, 32768, 64, 32768, 64],
     "dtype": "bfloat16", "platform": "tpu",
     "config": {"block_q": 1024, "block_k": 2048},
     "source": "default-docsweep"},
    {"kernel": "flash_attention", "sig": [1, 8, 8192, 64, 8192, 64],
     "dtype": "bfloat16", "platform": "tpu",
     "config": {"block_q": 1024, "block_k": 2048},
     "source": "default-docsweep"},
]


def cmd_tune(args):
    kern = _load_registry()
    from paddle_tpu.ops.kern import autotune
    from paddle_tpu.ops.pallas import flash_attention as fa
    if args.mode != "env":
        fa.set_mode(args.mode)
    entries = []
    for spec in _pick_specs(kern, args.kernels):
        ex = _example(spec)
        if ex is None or spec.signature is None:
            print(f"{spec.name}: not tunable, skipped")
            continue
        a, kw = ex
        cfg = autotune.autotune(spec, a, kw, repeats=args.repeats)
        rep = autotune.autotune.last_report or {}
        ran = [c for c in rep.get("candidates", []) if "ms" in c]
        if not cfg:
            print(f"{spec.name}: no candidate ran "
                  f"({len(rep.get('candidates', []))} tried)")
            continue
        key = rep["key"]
        best_ms = min(c["ms"] for c in ran)
        print(f"{spec.name}: best {cfg} @ {best_ms:.3f} ms "
              f"({len(ran)} candidates, platform {key[3]})")
        entries.append({"kernel": key[0], "sig": key[1],
                        "dtype": key[2], "platform": key[3],
                        "config": cfg, "source": "autotune",
                        "ms": best_ms})
    if args.tpu_defaults:
        entries.extend(_TPU_DEFAULTS)
    if args.emit_baseline:
        path = args.emit_baseline
        doc = {"schema": autotune.SCHEMA, "entries": []}
        try:
            with open(path) as f:
                old = json.load(f)
            if isinstance(old, dict) and old.get("schema") == \
                    autotune.SCHEMA:
                doc = old
        except (ValueError, OSError):
            pass
        # merge on the full key: new measurements replace old ones
        def _k(e):
            return json.dumps([e.get("kernel"), list(e.get("sig") or []),
                               e.get("dtype"), e.get("platform")],
                              sort_keys=True)
        index = {_k(e): e for e in doc.get("entries", [])}
        for e in entries:
            index[_k(e)] = e
        doc["entries"] = sorted(
            index.values(),
            key=lambda e: (e.get("kernel") or "", _k(e)))
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"baseline: {len(doc['entries'])} entries -> {path}")
    return 0


# ----------------------------------------------------------------- bench

def cmd_bench(args):
    if args.flash_ab:
        return _flash_ab(args)
    import numpy as np
    import jax
    kern = _load_registry()
    from paddle_tpu.ops.pallas import flash_attention as fa
    if args.mode != "env":
        fa.set_mode(args.mode)

    def med_ms(fn, operands):
        # jit only the arrays; scalars/flags stay static so the try_*
        # entries can branch on them
        arr_idx = [i for i, a in enumerate(operands)
                   if hasattr(a, "shape") and hasattr(a, "dtype")]
        arrs = [operands[i] for i in arr_idx]

        def run(*a):
            full = list(operands)
            for i, v in zip(arr_idx, a):
                full[i] = v
            return fn(*full)

        jfn = jax.jit(run)
        try:
            out = jfn(*arrs)
        except Exception as e:
            return f"error:{type(e).__name__}"
        if out is None or (isinstance(out, (tuple, list))
                           and all(o is None for o in out)):
            return None
        jax.block_until_ready(out)
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(jfn(*arrs))
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2] * 1e3

    for spec in _pick_specs(kern, args.kernels):
        ex = _example(spec)
        if ex is None:
            print(f"{spec.name:<22} (no example registered)")
            continue
        a, kw = ex
        k_ms = med_ms(lambda *o: spec.fn(*o, **kw), a)
        r_ms = med_ms(lambda *o: spec.reference(*o, **kw), a)
        if not isinstance(k_ms, float) or not isinstance(r_ms, float):
            print(f"{spec.name:<22} kernel={k_ms or 'rejected'}  "
                  f"reference={r_ms}")
            continue
        ok, detail = kern.parity_check(spec.name, a, kw)
        print(f"{spec.name:<22} kernel={k_ms:.3f} ms  "
              f"reference={r_ms:.3f} ms  "
              f"x{r_ms / max(k_ms, 1e-9):.2f}  parity={ok} ({detail})")
    return 0


def _flash_ab(args):
    """The retired tools/flash_ab.py measurement: causal fwd+bwd flash
    wall time + attn-MFU with the in-kernel probability exp in f32 vs
    bf16, and max|Δ| of loss and grads between the two."""
    import numpy as np

    def measure(T, dtype_name, repeats=3, inner=5):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas import flash_attention as fa
        import bench

        B, H, D = 1, 8, 64
        rng = np.random.RandomState(0)
        q, k, v = [jnp.asarray(rng.randn(B, H, T, D).astype("float32"),
                               jnp.bfloat16) for _ in range(3)]
        p_dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32
        # CPU smoke: force the Pallas interpreter when the real kernel
        # can't run (non-TPU backend); on the chip this stays False
        use_pallas, interpret = fa.active()
        interpret = interpret or not use_pallas

        def loss_fn(q, k, v):
            out = fa.flash_attention(q, k, v, causal=True,
                                     softmax_dtype=p_dtype,
                                     interpret=interpret)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        g = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1, 2)))
        val, grads = g(q, k, v)
        np.asarray(grads[0][0, 0, 0])  # completion barrier
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(inner):
                val, grads = g(q, k, v)
            np.asarray(grads[0][0, 0, 0])
            times.append((time.perf_counter() - t0) / inner)
        dt = sorted(times)[len(times) // 2]
        fl = 12 * B * H * T * T * D * 0.5  # causal fwd+bwd matmul flops
        peak = bench._peak_flops(jax.devices()[0])  # None on CPU smoke
        return {"ms": round(dt * 1e3, 2),
                "attn_mfu": round(fl / dt / peak, 4) if peak else None,
                "out": val, "grads": grads}

    report = {}
    for T in [int(s) for s in args.seqlens.split(",")]:
        f32 = measure(T, "f32")
        b16 = measure(T, "bf16")
        dg = max(float(np.max(np.abs(
            np.asarray(a, dtype=np.float32) -
            np.asarray(b, dtype=np.float32))))
            for a, b in zip(f32["grads"], b16["grads"]))
        report[f"T{T}"] = {
            "f32_ms": f32["ms"], "f32_attn_mfu": f32["attn_mfu"],
            "bf16_ms": b16["ms"], "bf16_attn_mfu": b16["attn_mfu"],
            "speedup": round(f32["ms"] / b16["ms"], 3),
            "loss_rel_delta": abs(float(f32["out"]) - float(b16["out"]))
            / max(abs(float(f32["out"])), 1e-9),
            "grad_max_abs_delta": dg,
        }
    print(json.dumps(report, indent=2))
    return 0


# -------------------------------------------------------------- selftest

def run_selftest():
    problems = []
    info = {}

    def check(ok, msg):
        if not ok:
            problems.append(msg)
        return ok

    kern = _load_registry()
    from paddle_tpu.ops.kern import autotune
    from paddle_tpu.ops.pallas import flash_attention as fa

    names = kern.names()
    info["kernels"] = names
    check(len(names) >= 5,
          f"registry holds {len(names)} kernels, expected >= 5")

    # 1) every kernel's static probe accepts its own example — on
    # ShapeDtypeStructs, the data-free path meshlint and `probe` use
    for spec in kern.specs():
        if not check(spec.example is not None,
                     f"{spec.name}: no example registered"):
            continue
        a, kw = _example(spec)
        check(bool(spec.probe(*_shape_structs(a), interpret=True, **kw)),
              f"{spec.name}: static probe rejects its own example")

    # 2) parity gate in interpret mode: kernel vs jnp reference
    fa.set_mode("interpret")
    try:
        parity = {}
        for spec in kern.specs():
            if spec.example is None:
                continue
            a, kw = _example(spec)
            ok, detail = kern.parity_check(spec.name, a, kw)
            parity[spec.name] = detail
            check(ok is True,
                  f"{spec.name}: parity gate failed ({detail})")
        info["parity"] = parity

        # 3) autotune cache round-trip on the cheapest tunable kernel.
        # The committed KERN_TUNED.json warm start is pointed away so
        # the disk-cache path (not the baseline) is what's exercised.
        spec = kern.get("int8_quant")
        a, kw = _example(spec)
        with tempfile.TemporaryDirectory() as tmp:
            old = os.environ.get(autotune.ENV_CACHE)
            old_base = os.environ.get(autotune.ENV_BASELINE)
            os.environ[autotune.ENV_CACHE] = tmp
            os.environ[autotune.ENV_BASELINE] = \
                os.path.join(tmp, "no_baseline.json")
            try:
                autotune.reset()
                cfg = autotune.autotune(spec, a, kw, repeats=1)
                if check(bool(cfg), "autotune found no legal config "
                         "for int8_quant"):
                    key = autotune.cache_key(spec, a, kw)
                    autotune.reset()   # force the disk read path
                    got = autotune.tuned_config(spec, a, kw)
                    check(got == cfg,
                          f"published config {cfg} did not round-trip "
                          f"({got})")
                    # torn entry: corrupt the payload -> validate()
                    # fails -> skipped, default blocks
                    d = os.path.join(tmp, key[0],
                                     autotune._digest(key))
                    with open(os.path.join(d, "tuned.json"), "w") as f:
                        f.write('{"torn": ')
                    autotune.reset()
                    rej0 = autotune.STATS["entries_rejected"]
                    got = autotune.tuned_config(spec, a, kw)
                    check(got == {},
                          f"torn cache entry was not rejected ({got})")
                    check(autotune.STATS["entries_rejected"] > rej0,
                          "torn entry not counted as rejected")
            finally:
                if old is None:
                    os.environ.pop(autotune.ENV_CACHE, None)
                else:
                    os.environ[autotune.ENV_CACHE] = old
                if old_base is None:
                    os.environ.pop(autotune.ENV_BASELINE, None)
                else:
                    os.environ[autotune.ENV_BASELINE] = old_base
                autotune.reset()
    finally:
        fa.set_mode("auto")

    # 4) the dispatch seam resolves every adapter key to its kernel
    from paddle_tpu.ops.kern import registry as kreg
    for key, name in kreg.ADAPTERS.items():
        check(kern.adapter(key) is not None,
              f"adapter key {key!r} does not resolve")
        check(name in kreg.KERN_SPECS,
              f"adapter key {key!r} points at unknown kernel {name!r}")
    return problems, info


# ------------------------------------------------------------------ main

def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--selftest", action="store_true",
                   help="run the CI gate assertions")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one machine-readable JSON verdict line")
    p.add_argument("--platform", default="cpu",
                   help="JAX_PLATFORMS to force ('env' keeps the "
                        "environment's; default cpu so the CLI never "
                        "hangs on a down relay)")
    sub = p.add_subparsers(dest="command")
    sub.add_parser("list", help="registered kernels")
    sp = sub.add_parser("probe", help="capability probes on examples")
    sp.add_argument("--kernels", default="", help="csv subset")
    st = sub.add_parser("tune", help="autotune block sizes")
    st.add_argument("--kernels", default="", help="csv subset")
    st.add_argument("--mode", default="env",
                    choices=["env", "auto", "interpret", "off"],
                    help="pallas mode for the timing run")
    st.add_argument("--repeats", type=int, default=3)
    st.add_argument("--emit-baseline", default=None, metavar="PATH",
                    help="write/merge the KERN_TUNED.json warm-start")
    st.add_argument("--tpu-defaults", action="store_true",
                    help="append the docsweep v5e default entries")
    sb = sub.add_parser("bench", help="kernel vs reference A/B")
    sb.add_argument("--kernels", default="", help="csv subset")
    sb.add_argument("--mode", default="env",
                    choices=["env", "auto", "interpret", "off"])
    sb.add_argument("--repeats", type=int, default=5)
    sb.add_argument("--flash-ab", action="store_true",
                    help="the retired tools/flash_ab.py f32-vs-bf16 "
                         "softmax A/B")
    sb.add_argument("--seqlens", default="8192,32768")
    args = p.parse_args(argv)

    if args.platform != "env":
        os.environ["JAX_PLATFORMS"] = args.platform

    if args.selftest:
        problems, info = run_selftest()
        result = {"ok": not problems, "problems": problems}
        result.update(info)
        if args.as_json:
            print(json.dumps(result, default=str))
        else:
            if problems:
                for prob in problems:
                    print(f"PROBLEM: {prob}", file=sys.stderr)
            else:
                print("tpukern: all checks passed "
                      f"({len(info.get('kernels', []))} kernels)")
        return 2 if problems else 0

    if args.command == "list":
        return cmd_list(args)
    if args.command == "probe":
        return cmd_probe(args)
    if args.command == "tune":
        return cmd_tune(args)
    if args.command == "bench":
        return cmd_bench(args)
    p.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
