#!/usr/bin/env python
"""tpuchaos — the fault-tolerance layer's CLI and CI gate.

Three jobs, in the tpustat/tpuserve/tpudoctor CLI tradition:

  demo        (default) train a small deterministic model under the
              Guardian, inject faults — a mid-step crash, a torn
              checkpoint write, a transient compile failure, a dead
              rank — and show the resilience layer surviving each:
              auto-resume from the last valid checkpoint, torn-write
              candidates skipped, retries absorbed, liveness flagged.
  worker      (internal) one deterministic Guardian training run in a
              subprocess — the kill -9 target. Faults come from
              PADDLE_TPU_CHAOS in the environment; on completion a
              result JSON (final loss, restarts) is written, so the
              parent can verify an interrupted-then-resumed job
              reaches the same loss as an uninterrupted one.
  --selftest  CI gate: all demo legs with assertions —
              (1) a run killed mid-step (in-process fault AND a real
                  SIGKILL'd subprocess) auto-resumes from the last
                  valid checkpoint and reaches a final loss within
                  tolerance of the uninterrupted run;
              (2) a checkpoint write torn at ANY injected byte offset
                  never leaves the root without a loadable restore
                  point (rotation GC keeps the last valid one);
              (3) transient compile faults are absorbed by the retry
                  engine (resilience.retry.* counters);
              (4) a silent rank turns into a typed FleetFault via the
                  spool-heartbeat liveness detector.
              One JSON verdict line with --json; exit 2 on any
              problem.

Examples:
  python tools/tpuchaos.py                         # demo
  python tools/tpuchaos.py --selftest --json       # CI gate
  PADDLE_TPU_CHAOS="step_fail:at=9,mode=kill" \\
      python tools/tpuchaos.py worker --root /tmp/ckpt --steps 12
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

STEPS = 12
SAVE_EVERY = 4
# chaos executor.step hit N: startup run is hit 1, training step k is
# hit k+2 -> at=9 crashes step 7, after the step-3 checkpoint landed
CRASH_AT = 9
LOSS_RTOL = 1e-4


# ------------------------------------------------------- training rig

def _build_model():
    import paddle_tpu as pt
    from paddle_tpu import layers
    main_p, startup_p = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup_p):
        with pt.unique_name.guard():
            x = layers.data("x", shape=[8])
            y = layers.data("y", shape=[1])
            h = layers.fc(x, 16, act="tanh")
            pred = layers.fc(h, 1)
            loss = layers.reduce_mean(
                layers.square_error_cost(pred, y))
            opt = pt.optimizer.Adam(1e-2)
            opt.minimize(loss)
    return main_p, startup_p, loss


def _feed_for_step(step):
    """Pure function of the step index — resumption replays the exact
    stream an uninterrupted run would have seen (the Guardian
    determinism contract)."""
    import numpy as np
    rng = np.random.RandomState(1000 + step)
    x = rng.rand(16, 8).astype("float32")
    y = (0.5 * x.sum(axis=1, keepdims=True)).astype("float32")
    return {"x": x, "y": y}


def _train_with_guardian(root, steps=STEPS, max_restarts=3):
    """One Guardian-supervised run in a fresh scope. Returns
    (final_loss, guardian)."""
    import paddle_tpu as pt
    from paddle_tpu.resilience import Guardian

    main_p, startup_p, loss = _build_model()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    with pt.scope_guard(scope):
        guardian = Guardian(exe, main_p, root,
                            startup_program=startup_p,
                            save_every=SAVE_EVERY,
                            max_restarts=max_restarts)

        def step_fn(step):
            out = exe.run(main_p, feed=_feed_for_step(step),
                          fetch_list=[loss])
            return float(out[0])

        final = guardian.run_with_recovery(step_fn, steps)
    return final, guardian


# ------------------------------------------------------------- worker

def cmd_worker(args):
    """Subprocess target: PADDLE_TPU_CHAOS in the env decides whether
    this run dies; a completed run writes the result JSON."""
    final, guardian = _train_with_guardian(args.root, steps=args.steps)
    result = {"final_loss": final, "steps": args.steps,
              "restarts": guardian.restarts,
              "restores": guardian.restore_count}
    path = args.result or os.path.join(args.root, "result.json")
    with open(path, "w") as f:
        json.dump(result, f)
    print(json.dumps(result))
    return 0


# ---------------------------------------------------------- demo legs

def run_demo(selftest=False):
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import telemetry as tm
    from paddle_tpu.io import CheckpointSaver, latest_checkpoint
    from paddle_tpu.resilience import (FleetFault, chaos, checkpoint,
                                       liveness)

    problems = []
    info = {}

    def check(ok, what):
        if not ok:
            problems.append(what)
        return ok

    def say(msg):
        if not selftest:
            print(msg)

    chaos.reset()
    check(not chaos.armed(), "chaos armed with no spec configured")

    # 1) baseline: uninterrupted run ---------------------------------
    base_root = tempfile.mkdtemp(prefix="tpuchaos_base_")
    base_loss, g0 = _train_with_guardian(base_root)
    info["baseline_loss"] = base_loss
    say(f"[baseline] {STEPS} uninterrupted steps, final loss "
        f"{base_loss:.6f}")
    check(g0.restarts == 0, "baseline run restarted")
    check(latest_checkpoint(base_root) is not None,
          "baseline run left no checkpoint")

    # 2) in-process crash at step 7 → Guardian auto-resume ------------
    crash_root = tempfile.mkdtemp(prefix="tpuchaos_crash_")
    chaos.configure(f"step_fail:at={CRASH_AT}")
    try:
        crash_loss, g1 = _train_with_guardian(crash_root)
    finally:
        chaos.reset()
    info["crash_resume_loss"] = crash_loss
    info["crash_restarts"] = g1.restarts
    say(f"[crash]    injected ChaosFault at step {CRASH_AT - 2}; "
        f"guardian restarted {g1.restarts}x, resumed from the last "
        f"valid checkpoint, final loss {crash_loss:.6f}")
    check(g1.restarts == 1, f"expected 1 restart, got {g1.restarts}")
    check(np.isclose(crash_loss, base_loss, rtol=LOSS_RTOL),
          f"crash-resumed loss {crash_loss} != baseline {base_loss}")

    # 3) kill -9 mid-step in a real subprocess → fresh-process resume -
    kill_root = tempfile.mkdtemp(prefix="tpuchaos_kill_")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_TPU_CHAOS=f"step_fail:at={CRASH_AT},mode=kill")
    env.pop("PADDLE_TPU_TELEMETRY", None)
    cmd = [sys.executable, os.path.abspath(__file__), "worker",
           "--root", kill_root, "--steps", str(STEPS)]
    p1 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                        timeout=300)
    check(p1.returncode == -signal.SIGKILL,
          f"worker exited {p1.returncode}, wanted -SIGKILL: "
          f"{p1.stderr[-300:]}")
    check(latest_checkpoint(kill_root) is not None,
          "killed worker left no valid checkpoint")
    env.pop("PADDLE_TPU_CHAOS")
    p2 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                        timeout=300)
    check(p2.returncode == 0,
          f"resume worker failed rc={p2.returncode}: "
          f"{p2.stderr[-300:]}")
    kill_loss = None
    try:
        with open(os.path.join(kill_root, "result.json")) as f:
            kill_loss = json.load(f)["final_loss"]
    except (OSError, ValueError, KeyError):
        problems.append("resumed worker wrote no result.json")
    info["kill9_resume_loss"] = kill_loss
    if kill_loss is not None:
        say(f"[kill -9]  subprocess SIGKILL'd mid-step, fresh process "
            f"auto-resumed, final loss {kill_loss:.6f}")
        check(np.isclose(kill_loss, base_loss, rtol=LOSS_RTOL),
              f"kill-9 resumed loss {kill_loss} != baseline "
              f"{base_loss}")

    # 4) torn-write sweep: never without a loadable restore point ----
    torn_root = tempfile.mkdtemp(prefix="tpuchaos_torn_")
    main_p, startup_p, _loss = _build_model()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup_p)
        saver = CheckpointSaver(torn_root, max_to_keep=2,
                                async_save=False)
        saver.save(exe, main_p, step=0)
        first = latest_checkpoint(torn_root)
        check(first is not None, "seed checkpoint invalid")
        psize = os.path.getsize(os.path.join(first, "params.npz"))
        offsets = sorted({0, 1, 37, psize // 3, psize // 2,
                          psize - 1, psize})
        torn_ok = 0
        for i, byte in enumerate(offsets):
            chaos.configure(f"ckpt_torn:byte={byte}")
            try:
                saver.save(exe, main_p, step=i + 1)
                problems.append(
                    f"torn write at byte {byte} did not surface")
            except RuntimeError:
                pass
            finally:
                chaos.reset()
            latest = latest_checkpoint(torn_root)
            if latest is None or not checkpoint.is_valid(latest):
                problems.append(
                    f"torn write at byte {byte} left no valid "
                    "restore point")
                continue
            torn_ok += 1
        # and the root still LOADS after the whole sweep
        try:
            meta = pt.io.load_checkpoint(exe, torn_root, main_p)
            check(meta["step"] == 0, "restored wrong checkpoint")
        except Exception as e:
            problems.append(f"post-sweep load failed: {e}")
    info["torn_offsets_survived"] = f"{torn_ok}/{len(offsets)}"
    say(f"[torn]     checkpoint writes torn at byte offsets "
        f"{offsets}: root kept a loadable restore point every time")

    # 5) transient compile faults absorbed by the retry engine -------
    import numpy as np  # noqa: F811 (readability in this long fn)
    retry_dir = tempfile.mkdtemp(prefix="tpuchaos_retry_")
    from paddle_tpu import layers
    from paddle_tpu.inference import InferenceEngine
    inf_main, inf_start = pt.Program(), pt.Program()
    with pt.program_guard(inf_main, inf_start):
        with pt.unique_name.guard():
            xv = layers.data("xv", shape=[4])
            pv = layers.fc(xv, 2, act="softmax")
    exe2 = pt.Executor(pt.CPUPlace())
    scope2 = pt.Scope()
    with pt.scope_guard(scope2):
        exe2.run(inf_start)
        pt.io.save_inference_model(retry_dir, ["xv"], [pv], exe2,
                                   main_program=inf_main)
    tm.enable()
    tm.reset()
    chaos.configure("compile_fail:at=1,times=2")
    try:
        eng = InferenceEngine.from_dir(retry_dir)
        out = eng.run({"xv": np.zeros((2, 4), "float32")})
        snap = tm.snapshot()
    finally:
        chaos.reset()
        tm.disable()
        tm.reset()
    check(len(out) == 1 and out[0].shape == (2, 2),
          "inference under injected compile faults returned garbage")
    retries = snap.get("resilience.retry.retries", 0)
    info["compile_retries"] = retries
    say(f"[retry]    2 injected transient compile failures absorbed "
        f"({retries} retries, then success)")
    check(retries == 2, f"expected 2 retries, counters say {retries}")

    # 6) dead-rank detection on a stale spool ------------------------
    import time
    spool = tempfile.mkdtemp(prefix="tpuchaos_spool_")
    now = time.time()
    for rank, age in ((0, 1.0), (1, 600.0)):
        path = os.path.join(spool, f"rank{rank:05d}.snap.json")
        with open(path, "w") as f:
            json.dump({"schema": "paddle_tpu.fleet.snapshot.v1",
                       "rank": rank,
                       "flush_unix_us": int((now - age) * 1e6),
                       "metrics": {}}, f)
        os.utime(path, (now - age, now - age))
    report = liveness.check_liveness(spool, stale_after_s=60.0,
                                     expected_world=3)
    info["liveness"] = report["verdict"]
    say(f"[liveness] {report['verdict']}")
    check(report["dead"] == [1], f"dead ranks {report['dead']} != [1]")
    check(report["missing"] == [2],
          f"missing ranks {report['missing']} != [2]")
    try:
        liveness.assert_alive(spool, stale_after_s=60.0,
                              expected_world=3)
        problems.append("assert_alive did not raise on a dead rank")
    except FleetFault as e:
        check(1 in e.ranks, "FleetFault does not name the dead rank")

    return problems, info


# ---------------------------------------------------------------- main

def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("command", nargs="?", default="demo",
                   choices=["demo", "worker"])
    p.add_argument("--root", default=None,
                   help="checkpoint root (worker)")
    p.add_argument("--steps", type=int, default=STEPS)
    p.add_argument("--result", default=None,
                   help="result JSON path (worker; default "
                        "<root>/result.json)")
    p.add_argument("--selftest", action="store_true",
                   help="run the CI gate assertions")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one machine-readable JSON verdict line")
    p.add_argument("--platform", default="cpu",
                   help="JAX_PLATFORMS to force ('env' keeps the "
                        "environment's; default cpu so the CLI never "
                        "hangs on a down relay)")
    args = p.parse_args(argv)

    if args.platform != "env":
        os.environ["JAX_PLATFORMS"] = args.platform

    if args.command == "worker":
        if not args.root:
            p.error("worker needs --root")
        return cmd_worker(args)

    problems, info = run_demo(selftest=args.selftest)
    result = {"ok": not problems, "problems": problems}
    result.update(info)
    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        if problems:
            for prob in problems:
                print(f"PROBLEM: {prob}", file=sys.stderr)
        else:
            print("tpuchaos: all checks passed "
                  f"(baseline {info['baseline_loss']:.6f} == "
                  f"crash-resume {info['crash_resume_loss']:.6f} == "
                  f"kill-9-resume {info['kill9_resume_loss']:.6f})")
    return 2 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
