#!/usr/bin/env python
"""tpuchaos — the fault-tolerance layer's CLI and CI gate.

Three jobs, in the tpustat/tpuserve/tpudoctor CLI tradition:

  demo        (default) train a small deterministic model under the
              Guardian, inject faults — a mid-step crash, a torn
              checkpoint write, a transient compile failure, a dead
              rank — and show the resilience layer surviving each:
              auto-resume from the last valid checkpoint, torn-write
              candidates skipped, retries absorbed, liveness flagged.
  worker      (internal) one deterministic Guardian training run in a
              subprocess — the kill -9 target. Faults come from
              PADDLE_TPU_CHAOS in the environment; on completion a
              result JSON (final loss, restarts) is written, so the
              parent can verify an interrupted-then-resumed job
              reaches the same loss as an uninterrupted one.
  elastic-worker
              (internal) one phase of the elastic selftest: a
              Guardian-supervised sparse-embedding training run over a
              --world-member mesh (first W of the 8 virtual CPU
              devices), resuming from whatever topology-independent
              checkpoint the root holds — written at ANY world size.
              PADDLE_TPU_CHAOS decides whether a rank is lost (SIGKILL)
              or a resize request arrives (exit 17 + resize.json).
  --selftest-elastic
              the elastic CI gate (ROADMAP item 4): N=8 training loses
              rank 3 to a SIGKILL mid-step; the coordinator detects the
              silence via liveness, re-forms at N=6, and the run resumes
              from the world-8 checkpoint through the streaming
              r%8 -> r%6 shard shuffle; a resize request then grows the
              fleet back to N=8 (r%6 -> r%8). Asserts the final loss is
              within tolerance of an uninterrupted N=8 run and that the
              per-row embedding fingerprints survive BOTH shuffles
              byte-for-byte (zero lost rows).
  --selftest  CI gate: all demo legs with assertions —
              (1) a run killed mid-step (in-process fault AND a real
                  SIGKILL'd subprocess) auto-resumes from the last
                  valid checkpoint and reaches a final loss within
                  tolerance of the uninterrupted run;
              (2) a checkpoint write torn at ANY injected byte offset
                  never leaves the root without a loadable restore
                  point (rotation GC keeps the last valid one);
              (3) transient compile faults are absorbed by the retry
                  engine (resilience.retry.* counters);
              (4) a silent rank turns into a typed FleetFault via the
                  spool-heartbeat liveness detector.
              One JSON verdict line with --json; exit 2 on any
              problem.

Examples:
  python tools/tpuchaos.py                         # demo
  python tools/tpuchaos.py --selftest --json       # CI gate
  PADDLE_TPU_CHAOS="step_fail:at=9,mode=kill" \\
      python tools/tpuchaos.py worker --root /tmp/ckpt --steps 12
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

STEPS = 12
SAVE_EVERY = 4
# chaos executor.step hit N: startup run is hit 1, training step k is
# hit k+2 -> at=9 crashes step 7, after the step-3 checkpoint landed
CRASH_AT = 9
LOSS_RTOL = 1e-4

# ---- elastic selftest rig (N=8 -> 6 -> 8, ROADMAP item 4) ----------
E_VOCAB = 50          # % 8 != 0 and % 6 != 0: pad rows exercised
E_DIM = 8
E_BATCH = 24          # divisible by every world in E_CHOICES
E_FIELDS = 4
E_STEPS = 12
E_SAVE_EVERY = 3
E_CHOICES = (8, 6, 4, 2)
# phase A (world 8): startup hit 1, step k is hit k+2 -> at=9 kills
# step 7, after the step-5 checkpoint (done=6) landed -> resume at 6
E_KILL_AT = 9
# phase B (world 6) resumes at step 6: startup hit 1, step 6+k is hit
# k+2 -> at=6 fires the resize at step 10, after the step-8 checkpoint
E_RESIZE_AT = 6
# loss reassociation across world sizes (pmean of 3-member means vs
# 4-member means) is ~1e-7/step; 1e-3 leaves SGD drift headroom
E_LOSS_RTOL = 1e-3
EXIT_RESIZE = 17      # elastic-worker: "re-form me at resize.json:to"


# ------------------------------------------------------- training rig

def _build_model():
    import paddle_tpu as pt
    from paddle_tpu import layers
    main_p, startup_p = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup_p):
        with pt.unique_name.guard():
            x = layers.data("x", shape=[8])
            y = layers.data("y", shape=[1])
            h = layers.fc(x, 16, act="tanh")
            pred = layers.fc(h, 1)
            loss = layers.reduce_mean(
                layers.square_error_cost(pred, y))
            opt = pt.optimizer.Adam(1e-2)
            opt.minimize(loss)
    return main_p, startup_p, loss


def _feed_for_step(step):
    """Pure function of the step index — resumption replays the exact
    stream an uninterrupted run would have seen (the Guardian
    determinism contract)."""
    import numpy as np
    rng = np.random.RandomState(1000 + step)
    x = rng.rand(16, 8).astype("float32")
    y = (0.5 * x.sum(axis=1, keepdims=True)).astype("float32")
    return {"x": x, "y": y}


def _train_with_guardian(root, steps=STEPS, max_restarts=3):
    """One Guardian-supervised run in a fresh scope. Returns
    (final_loss, guardian)."""
    import paddle_tpu as pt
    from paddle_tpu.resilience import Guardian

    main_p, startup_p, loss = _build_model()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    with pt.scope_guard(scope):
        guardian = Guardian(exe, main_p, root,
                            startup_program=startup_p,
                            save_every=SAVE_EVERY,
                            max_restarts=max_restarts)

        def step_fn(step):
            out = exe.run(main_p, feed=_feed_for_step(step),
                          fetch_list=[loss])
            return float(out[0])

        final = guardian.run_with_recovery(step_fn, steps)
    return final, guardian


# ------------------------------------------------------------- worker

def cmd_worker(args):
    """Subprocess target: PADDLE_TPU_CHAOS in the env decides whether
    this run dies; a completed run writes the result JSON."""
    final, guardian = _train_with_guardian(args.root, steps=args.steps)
    result = {"final_loss": final, "steps": args.steps,
              "restarts": guardian.restarts,
              "restores": guardian.restore_count}
    path = args.result or os.path.join(args.root, "result.json")
    with open(path, "w") as f:
        json.dump(result, f)
    print(json.dumps(result))
    return 0


# ---------------------------------------------------- elastic worker

def _build_elastic_model(seed=17):
    """Sparse-embedding model for the elastic rig: a mod-sharded
    distributed table under the tpusparse engine — the state whose
    r%N -> r%M shuffle the selftest audits row by row."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    main_p, startup_p = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup_p):
        with pt.unique_name.guard():
            ids = layers.data("ids", shape=[E_FIELDS, 1], dtype="int64")
            y = layers.data("y", shape=[E_DIM], dtype="float32")
            emb = layers.embedding(
                ids, size=[E_VOCAB, E_DIM], is_sparse=True,
                is_distributed=True,
                param_attr=pt.ParamAttr(name="etbl"))
            loss = layers.reduce_mean(layers.square_error_cost(
                layers.reduce_sum(emb, dim=1), y))
            pt.optimizer.SGD(0.1).minimize(loss)
    main_p.random_seed = startup_p.random_seed = seed
    return main_p, startup_p, loss


def _elastic_feed(step):
    """Pure function of the step index (the Guardian determinism
    contract) with a GLOBAL batch divisible by every world size in
    E_CHOICES — resumption at any N replays the same stream."""
    import numpy as np
    rng = np.random.RandomState(7000 + step)
    ids = rng.randint(0, E_VOCAB,
                      (E_BATCH, E_FIELDS, 1)).astype("int64")
    y = rng.randn(E_BATCH, E_DIM).astype("float32")
    return {"ids": ids, "y": y}


def cmd_elastic_worker(args):
    """One phase of the elastic run: Guardian-supervised training over
    a --world-member mesh, resumed from whatever topology-independent
    checkpoint --root holds (written at ANY world size — the restore
    streams the r%N -> r%M shuffle). A rank_lost:mode=kill fault dies
    mid-step; a resize fault exits EXIT_RESIZE with resize.json so the
    coordinator re-forms at the requested size."""
    import numpy as np
    import jax
    import paddle_tpu as pt
    from paddle_tpu.parallel.mesh import local_mesh
    from paddle_tpu.resilience import Guardian, chaos
    from paddle_tpu.resilience import elastic

    world = args.world
    main_p, startup_p, loss = _build_elastic_model()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup_p)
        mesh = local_mesh("dp", devices=jax.devices()[:world])
        pexe = pt.ParallelExecutor(loss_name=loss.name,
                                   main_program=main_p, scope=scope,
                                   mesh=mesh, sparse="shard")
        guardian = Guardian(pexe, main_p, args.root,
                            save_every=E_SAVE_EVERY, max_restarts=3)

        def logical_tables():
            eng = pexe.sparse_engine
            return {name: eng.to_logical(eng.owner_table(name),
                                         np.asarray(scope.get(name)))
                    for name in eng.row_var_names
                    if scope.get(name) is not None}

        if args.dump_restore:
            # audit hook: restore NOW and fingerprint the re-sharded
            # rows before any training step touches them — the parent
            # compares these against the checkpoint's own fingerprints
            # (zero-lost-rows). run_with_recovery restores again
            # (idempotent) below.
            resumed = guardian.restore()
            fps = {n: [int(x) for x in elastic.fingerprint_array(a)]
                   for n, a in logical_tables().items()}
            with open(args.dump_restore, "w") as f:
                json.dump({"resume_at": resumed, "world": world,
                           "fingerprints": fps}, f)

        def step_fn(step):
            out = pexe.run(feed=_elastic_feed(step), fetch_list=[loss])
            return float(np.asarray(out[0]))

        try:
            final = guardian.run_with_recovery(step_fn, args.steps)
        except chaos.ResizeFault as e:
            # a planned grow/shrink: hand the requested size back to
            # the coordinator; the last periodic checkpoint is the
            # resume point (deterministic feeds replay the gap)
            with open(os.path.join(args.root, "resize.json"), "w") as f:
                json.dump({"to": e.to, "world": world}, f)
            return EXIT_RESIZE
        table = logical_tables()["etbl"]
    result = {"final_loss": final, "steps": args.steps, "world": world,
              "restarts": guardian.restarts,
              "table": np.asarray(table, dtype=float).tolist()}
    path = args.result or os.path.join(args.root, "result.json")
    with open(path, "w") as f:
        json.dump(result, f)
    print(json.dumps({"final_loss": final, "world": world}))
    return 0


# ---------------------------------------------------------- demo legs

def run_demo(selftest=False):
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import telemetry as tm
    from paddle_tpu.io import CheckpointSaver, latest_checkpoint
    from paddle_tpu.resilience import (FleetFault, chaos, checkpoint,
                                       liveness)

    problems = []
    info = {}

    def check(ok, what):
        if not ok:
            problems.append(what)
        return ok

    def say(msg):
        if not selftest:
            print(msg)

    chaos.reset()
    check(not chaos.armed(), "chaos armed with no spec configured")

    # 1) baseline: uninterrupted run ---------------------------------
    base_root = tempfile.mkdtemp(prefix="tpuchaos_base_")
    base_loss, g0 = _train_with_guardian(base_root)
    info["baseline_loss"] = base_loss
    say(f"[baseline] {STEPS} uninterrupted steps, final loss "
        f"{base_loss:.6f}")
    check(g0.restarts == 0, "baseline run restarted")
    check(latest_checkpoint(base_root) is not None,
          "baseline run left no checkpoint")

    # 2) in-process crash at step 7 → Guardian auto-resume ------------
    crash_root = tempfile.mkdtemp(prefix="tpuchaos_crash_")
    chaos.configure(f"step_fail:at={CRASH_AT}")
    try:
        crash_loss, g1 = _train_with_guardian(crash_root)
    finally:
        chaos.reset()
    info["crash_resume_loss"] = crash_loss
    info["crash_restarts"] = g1.restarts
    say(f"[crash]    injected ChaosFault at step {CRASH_AT - 2}; "
        f"guardian restarted {g1.restarts}x, resumed from the last "
        f"valid checkpoint, final loss {crash_loss:.6f}")
    check(g1.restarts == 1, f"expected 1 restart, got {g1.restarts}")
    check(np.isclose(crash_loss, base_loss, rtol=LOSS_RTOL),
          f"crash-resumed loss {crash_loss} != baseline {base_loss}")

    # 3) kill -9 mid-step in a real subprocess → fresh-process resume -
    kill_root = tempfile.mkdtemp(prefix="tpuchaos_kill_")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_TPU_CHAOS=f"step_fail:at={CRASH_AT},mode=kill")
    env.pop("PADDLE_TPU_TELEMETRY", None)
    cmd = [sys.executable, os.path.abspath(__file__), "worker",
           "--root", kill_root, "--steps", str(STEPS)]
    p1 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                        timeout=300)
    check(p1.returncode == -signal.SIGKILL,
          f"worker exited {p1.returncode}, wanted -SIGKILL: "
          f"{p1.stderr[-300:]}")
    check(latest_checkpoint(kill_root) is not None,
          "killed worker left no valid checkpoint")
    env.pop("PADDLE_TPU_CHAOS")
    p2 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                        timeout=300)
    check(p2.returncode == 0,
          f"resume worker failed rc={p2.returncode}: "
          f"{p2.stderr[-300:]}")
    kill_loss = None
    try:
        with open(os.path.join(kill_root, "result.json")) as f:
            kill_loss = json.load(f)["final_loss"]
    except (OSError, ValueError, KeyError):
        problems.append("resumed worker wrote no result.json")
    info["kill9_resume_loss"] = kill_loss
    if kill_loss is not None:
        say(f"[kill -9]  subprocess SIGKILL'd mid-step, fresh process "
            f"auto-resumed, final loss {kill_loss:.6f}")
        check(np.isclose(kill_loss, base_loss, rtol=LOSS_RTOL),
              f"kill-9 resumed loss {kill_loss} != baseline "
              f"{base_loss}")

    # 4) torn-write sweep: never without a loadable restore point ----
    torn_root = tempfile.mkdtemp(prefix="tpuchaos_torn_")
    main_p, startup_p, _loss = _build_model()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup_p)
        saver = CheckpointSaver(torn_root, max_to_keep=2,
                                async_save=False)
        saver.save(exe, main_p, step=0)
        first = latest_checkpoint(torn_root)
        check(first is not None, "seed checkpoint invalid")
        psize = os.path.getsize(os.path.join(first, "params.npz"))
        offsets = sorted({0, 1, 37, psize // 3, psize // 2,
                          psize - 1, psize})
        torn_ok = 0
        for i, byte in enumerate(offsets):
            chaos.configure(f"ckpt_torn:byte={byte}")
            try:
                saver.save(exe, main_p, step=i + 1)
                problems.append(
                    f"torn write at byte {byte} did not surface")
            except RuntimeError:
                pass
            finally:
                chaos.reset()
            latest = latest_checkpoint(torn_root)
            if latest is None or not checkpoint.is_valid(latest):
                problems.append(
                    f"torn write at byte {byte} left no valid "
                    "restore point")
                continue
            torn_ok += 1
        # and the root still LOADS after the whole sweep
        try:
            meta = pt.io.load_checkpoint(exe, torn_root, main_p)
            check(meta["step"] == 0, "restored wrong checkpoint")
        except Exception as e:
            problems.append(f"post-sweep load failed: {e}")
    info["torn_offsets_survived"] = f"{torn_ok}/{len(offsets)}"
    say(f"[torn]     checkpoint writes torn at byte offsets "
        f"{offsets}: root kept a loadable restore point every time")

    # 5) transient compile faults absorbed by the retry engine -------
    import numpy as np  # noqa: F811 (readability in this long fn)
    retry_dir = tempfile.mkdtemp(prefix="tpuchaos_retry_")
    from paddle_tpu import layers
    from paddle_tpu.inference import InferenceEngine
    inf_main, inf_start = pt.Program(), pt.Program()
    with pt.program_guard(inf_main, inf_start):
        with pt.unique_name.guard():
            xv = layers.data("xv", shape=[4])
            pv = layers.fc(xv, 2, act="softmax")
    exe2 = pt.Executor(pt.CPUPlace())
    scope2 = pt.Scope()
    with pt.scope_guard(scope2):
        exe2.run(inf_start)
        pt.io.save_inference_model(retry_dir, ["xv"], [pv], exe2,
                                   main_program=inf_main)
    tm.enable()
    tm.reset()
    chaos.configure("compile_fail:at=1,times=2")
    try:
        eng = InferenceEngine.from_dir(retry_dir)
        out = eng.run({"xv": np.zeros((2, 4), "float32")})
        snap = tm.snapshot()
    finally:
        chaos.reset()
        tm.disable()
        tm.reset()
    check(len(out) == 1 and out[0].shape == (2, 2),
          "inference under injected compile faults returned garbage")
    retries = snap.get("resilience.retry.retries", 0)
    info["compile_retries"] = retries
    say(f"[retry]    2 injected transient compile failures absorbed "
        f"({retries} retries, then success)")
    check(retries == 2, f"expected 2 retries, counters say {retries}")

    # 6) dead-rank detection on a stale spool ------------------------
    import time
    spool = tempfile.mkdtemp(prefix="tpuchaos_spool_")
    now = time.time()
    for rank, age in ((0, 1.0), (1, 600.0)):
        path = os.path.join(spool, f"rank{rank:05d}.snap.json")
        with open(path, "w") as f:
            json.dump({"schema": "paddle_tpu.fleet.snapshot.v1",
                       "rank": rank,
                       "flush_unix_us": int((now - age) * 1e6),
                       "metrics": {}}, f)
        os.utime(path, (now - age, now - age))
    report = liveness.check_liveness(spool, stale_after_s=60.0,
                                     expected_world=3)
    info["liveness"] = report["verdict"]
    say(f"[liveness] {report['verdict']}")
    check(report["dead"] == [1], f"dead ranks {report['dead']} != [1]")
    check(report["missing"] == [2],
          f"missing ranks {report['missing']} != [2]")
    try:
        liveness.assert_alive(spool, stale_after_s=60.0,
                              expected_world=3)
        problems.append("assert_alive did not raise on a dead rank")
    except FleetFault as e:
        check(1 in e.ranks, "FleetFault does not name the dead rank")

    return problems, info


# ------------------------------------------------------- elastic legs

def _ckpt_fingerprints(path):
    """(fingerprints, world_size) straight from a checkpoint's shard
    files — per logical row, streamed shard by shard (the parent-side
    half of the zero-lost-rows audit)."""
    from paddle_tpu.resilience import elastic
    with open(os.path.join(path, "checkpoint.json")) as f:
        meta = json.load(f)
    fps = {}
    for name, rec in sorted(meta.get("layout", {}).items()):
        read = elastic.read_shard_fn(path, rec)
        fps[name] = [int(x) for x in elastic.fingerprint_rows(
            read, rec["world"], rec["vocab"])]
    return fps, meta.get("world_size")


def run_elastic_demo(selftest=False):
    """The N=8 -> 6 -> 8 gate: rank loss, liveness detection, shrink,
    resize request, grow — every transition through the topology-
    independent checkpoint, with loss-tolerance and per-row-fingerprint
    assertions."""
    import time

    import numpy as np
    from paddle_tpu.io import latest_checkpoint
    from paddle_tpu.resilience import elastic, liveness

    problems = []
    info = {}

    def check(ok, what):
        if not ok:
            problems.append(what)
        return ok

    def say(msg):
        if not selftest:
            print(msg)

    base_root = tempfile.mkdtemp(prefix="tpuelastic_base_")
    run_root = tempfile.mkdtemp(prefix="tpuelastic_run_")
    spool = os.path.join(run_root, "spool")

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_TPU_TELEMETRY="1",
               PADDLE_TPU_FLEET_RANK="0",
               PADDLE_TPU_FLEET_WORLD="1",
               PADDLE_TPU_FLEET_DIR=spool,
               PADDLE_TPU_FLEET_FLUSH_S="0.05")
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    env.pop("PADDLE_TPU_CHAOS", None)

    def worker(world, root, chaos_spec=None, dump=None):
        e = dict(env)
        if chaos_spec:
            e["PADDLE_TPU_CHAOS"] = chaos_spec
        cmd = [sys.executable, os.path.abspath(__file__),
               "elastic-worker", "--root", root, "--world", str(world),
               "--steps", str(E_STEPS)]
        if dump:
            cmd += ["--dump-restore", dump]
        return subprocess.run(cmd, env=e, capture_output=True,
                              text=True, timeout=300)

    # [baseline] uninterrupted N=8 run ------------------------------
    p = worker(8, base_root)
    check(p.returncode == 0,
          f"elastic baseline failed rc={p.returncode}: "
          f"{p.stderr[-400:]}")
    base = {}
    try:
        with open(os.path.join(base_root, "result.json")) as f:
            base = json.load(f)
    except (OSError, ValueError):
        problems.append("elastic baseline wrote no result.json")
    info["elastic_baseline_loss"] = base.get("final_loss")
    say(f"[baseline] {E_STEPS} uninterrupted steps at N=8, final loss "
        f"{base.get('final_loss', float('nan')):.6f}")

    coordinator = elastic.ElasticCoordinator(run_root, world=8,
                                             choices=E_CHOICES)

    # [phase A] rank 3 preempted (real SIGKILL) at N=8 ---------------
    p = worker(8, run_root,
               chaos_spec=f"rank_lost:rank=3,at={E_KILL_AT},mode=kill")
    check(p.returncode == -signal.SIGKILL,
          f"rank_lost worker exited {p.returncode}, wanted -SIGKILL: "
          f"{p.stderr[-400:]}")
    # the dead worker's heartbeat goes stale -> liveness turns the
    # silence into a typed report BEFORE anything hangs on it
    time.sleep(1.0)
    report = liveness.check_liveness(spool, stale_after_s=0.5,
                                     expected_ranks=[0])
    check(not report["ok"],
          "liveness did not flag the SIGKILL'd worker's stale spool")
    ck8 = latest_checkpoint(run_root)
    check(ck8 is not None, "killed run left no valid checkpoint")
    fps8, world8 = _ckpt_fingerprints(ck8) if ck8 else ({}, None)
    check(world8 == 8, f"checkpoint world_size {world8} != 8")
    plan = coordinator.plan_after_loss([3])
    check(plan.new_world == 6,
          f"plan after 1 lost rank chose {plan.new_world}, wanted 6 "
          f"(choices {E_CHOICES})")
    coordinator.reform(plan)
    say(f"[rank lost] rank 3 SIGKILL'd at N=8 step {E_KILL_AT - 2}; "
        f"liveness: {report['verdict']}; plan: {plan.reason}")

    # [phase B] resume at N=6; a grow request arrives mid-run --------
    dump6 = os.path.join(run_root, "dump6.json")
    p = worker(coordinator.world, run_root,
               chaos_spec=f"resize:to=8,at={E_RESIZE_AT}", dump=dump6)
    check(p.returncode == EXIT_RESIZE,
          f"resize worker exited {p.returncode}, wanted {EXIT_RESIZE}: "
          f"{p.stderr[-400:]}")
    d6 = {}
    try:
        with open(dump6) as f:
            d6 = json.load(f)
    except (OSError, ValueError):
        problems.append("N=6 worker wrote no restore dump")
    check(d6.get("resume_at") not in (None, 0),
          f"N=6 run did not resume from the N=8 checkpoint "
          f"(resume_at={d6.get('resume_at')})")
    check(d6.get("fingerprints") == fps8,
          "embedding rows lost/changed in the r%8 -> r%6 shuffle")
    ck6 = latest_checkpoint(run_root)
    fps6, world6 = _ckpt_fingerprints(ck6) if ck6 else ({}, None)
    check(world6 == 6, f"post-shrink checkpoint world_size {world6}")
    try:
        with open(os.path.join(run_root, "resize.json")) as f:
            resize_to = json.load(f)["to"]
    except (OSError, ValueError, KeyError):
        resize_to = 8
        problems.append("resize worker wrote no resize.json")
    coordinator.reform(coordinator.plan_resize(resize_to))
    say(f"[shrink]   resumed at N=6 from step {d6.get('resume_at')} "
        f"(rows intact); resize request -> grow back to {resize_to}")

    # [phase C] back at N=8, run to completion -----------------------
    dump8 = os.path.join(run_root, "dump8.json")
    p = worker(coordinator.world, run_root, dump=dump8)
    check(p.returncode == 0,
          f"grow-back worker failed rc={p.returncode}: "
          f"{p.stderr[-400:]}")
    d8 = {}
    try:
        with open(dump8) as f:
            d8 = json.load(f)
    except (OSError, ValueError):
        problems.append("N=8 grow-back worker wrote no restore dump")
    check(d8.get("fingerprints") == fps6,
          "embedding rows lost/changed in the r%6 -> r%8 shuffle")
    res = {}
    try:
        with open(os.path.join(run_root, "result.json")) as f:
            res = json.load(f)
    except (OSError, ValueError):
        problems.append("elastic run wrote no final result.json")
    info["elastic_final_loss"] = res.get("final_loss")
    info["elastic_worlds"] = coordinator.history
    if res.get("final_loss") is not None and \
            base.get("final_loss") is not None:
        check(np.isclose(res["final_loss"], base["final_loss"],
                         rtol=E_LOSS_RTOL),
              f"elastic final loss {res['final_loss']} vs baseline "
              f"{base['final_loss']} outside rtol={E_LOSS_RTOL}")
        check(np.allclose(np.asarray(res.get("table", [])),
                          np.asarray(base.get("table", [])),
                          rtol=1e-2, atol=1e-4),
              "final embedding table diverged from the uninterrupted "
              "run beyond tolerance")
    say(f"[grow]     resumed at N=8 from step {d8.get('resume_at')}, "
        f"final loss {res.get('final_loss', float('nan')):.6f} "
        f"(baseline {base.get('final_loss', float('nan')):.6f}); "
        f"world history {coordinator.history}")
    return problems, info


# ---------------------------------------------------------------- main

def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("command", nargs="?", default="demo",
                   choices=["demo", "worker", "elastic-worker"])
    p.add_argument("--root", default=None,
                   help="checkpoint root (worker)")
    p.add_argument("--steps", type=int, default=STEPS)
    p.add_argument("--result", default=None,
                   help="result JSON path (worker; default "
                        "<root>/result.json)")
    p.add_argument("--world", type=int, default=8,
                   help="mesh size (elastic-worker): first W of the "
                        "local devices")
    p.add_argument("--dump-restore", default=None,
                   help="elastic-worker: restore immediately and dump "
                        "resume step + per-row table fingerprints to "
                        "this JSON before training (the zero-lost-rows "
                        "audit)")
    p.add_argument("--selftest", action="store_true",
                   help="run the CI gate assertions")
    p.add_argument("--selftest-elastic", action="store_true",
                   dest="selftest_elastic",
                   help="run the elastic N=8 -> 6 -> 8 gate")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one machine-readable JSON verdict line")
    p.add_argument("--platform", default="cpu",
                   help="JAX_PLATFORMS to force ('env' keeps the "
                        "environment's; default cpu so the CLI never "
                        "hangs on a down relay)")
    args = p.parse_args(argv)

    if args.platform != "env":
        os.environ["JAX_PLATFORMS"] = args.platform
    if args.command == "elastic-worker" or args.selftest_elastic:
        # the elastic rig simulates the mesh with 8 virtual CPU
        # devices (tests/conftest.py's trick) — must land before the
        # first jax import, which all happen inside the commands
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    if args.command == "worker":
        if not args.root:
            p.error("worker needs --root")
        return cmd_worker(args)
    if args.command == "elastic-worker":
        if not args.root:
            p.error("elastic-worker needs --root")
        return cmd_elastic_worker(args)

    if args.selftest_elastic:
        problems, info = run_elastic_demo(
            selftest=args.selftest or args.as_json)
        result = {"ok": not problems, "problems": problems}
        result.update(info)
        if args.as_json:
            print(json.dumps(result, default=str))
        elif problems:
            for prob in problems:
                print(f"PROBLEM: {prob}", file=sys.stderr)
        else:
            print("tpuchaos elastic: all checks passed "
                  f"(worlds {info['elastic_worlds']}, baseline "
                  f"{info['elastic_baseline_loss']:.6f} ~= elastic "
                  f"{info['elastic_final_loss']:.6f}, zero lost rows)")
        return 2 if problems else 0

    problems, info = run_demo(selftest=args.selftest)
    result = {"ok": not problems, "problems": problems}
    result.update(info)
    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        if problems:
            for prob in problems:
                print(f"PROBLEM: {prob}", file=sys.stderr)
        else:
            print("tpuchaos: all checks passed "
                  f"(baseline {info['baseline_loss']:.6f} == "
                  f"crash-resume {info['crash_resume_loss']:.6f} == "
                  f"kill-9-resume {info['kill9_resume_loss']:.6f})")
    return 2 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
