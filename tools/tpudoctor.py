#!/usr/bin/env python
"""tpudoctor — the training-numerics doctor's CLI.

Three jobs:

  demo        (default) build the benchmark MNIST MLP, train a few
              healthy steps with the health monitor, then inject a
              numeric failure and show the doctor localizing it to the
              exact culprit op — NumericsReport + flight-recorder dump.
  postmortem  pretty-print a flight-recorder JSON dump
              (PADDLE_TPU_FLIGHT_RECORDER=<dir> writes them on NaN,
              uncaught exception, or exit).
  --selftest  CI gate (pattern of tools/tpuserve.py --selftest): runs
              the demo with assertions — culprit localized to the
              exact op type + block/op index, the NanInfError report is
              complete, the dump round-trips through this printer, and
              a diagnostics-off run takes zero snapshots. One JSON
              verdict line with --json; exit 2 on any problem.

Examples:
  python tools/tpudoctor.py                      # demo
  python tools/tpudoctor.py postmortem flight_recorder/flight_123.json
  python tools/tpudoctor.py --selftest --json
"""
import argparse
import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


# ------------------------------------------------------------ postmortem

def format_dump(payload):
    """Human-readable rendering of a flight-recorder dump payload."""
    records = payload.get("records", [])
    lines = [
        f"flight recorder dump — reason: {payload.get('reason')}, "
        f"pid {payload.get('pid')}, uptime "
        f"{payload.get('uptime_s', '?')}s, {len(records)} record(s) "
        f"(ring capacity {payload.get('capacity')})"
    ]
    events = payload.get("events", [])
    if events:
        lines.append("events:")
        for e in events[-16:]:
            extra = {k: v for k, v in e.items()
                     if k not in ("kind", "t")}
            lines.append(f"  [{e.get('t', 0):>9.3f}s] {e.get('kind')} "
                         + json.dumps(extra, default=str))
    if records:
        cols = ("step", "loss", "grad_norm", "update_ratio", "step_s",
                "compile", "program")
        if any("hbm" in r for r in records):
            cols += ("hbm",)    # memory-ledger runs watermark the ring
        lines.append("last steps:")
        lines.append("  " + "  ".join(f"{c:>12}" for c in cols))
        for r in records[-12:]:
            row = []
            for c in cols:
                v = r.get(c)
                if isinstance(v, float):
                    row.append(f"{v:>12.5g}")
                else:
                    row.append(f"{str(v) if v is not None else '-':>12}")
            lines.append("  " + "  ".join(row))
    if payload.get("report"):
        from paddle_tpu.diagnostics import NumericsReport
        lines.append("attached numerics report:")
        lines.append(NumericsReport.from_dict(payload["report"]).format())
    if payload.get("error"):
        lines.append("error:")
        lines.append(str(payload["error"]).rstrip())
    return "\n".join(lines)


def cmd_postmortem(path):
    with open(path) as f:
        payload = json.load(f)
    print(format_dump(payload))
    return 0


# ------------------------------------------------------------------ demo

def _build_mnist(health=True):
    import paddle_tpu as pt
    from paddle_tpu.models import mnist
    main_p, startup_p = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup_p):
        with pt.unique_name.guard():
            feeds, loss, acc = mnist.build_program(model="mlp")
            opt = pt.optimizer.Adam(1e-3)
            opt.minimize(loss, health=health)
    return main_p, startup_p, loss, opt


def _healthy_steps(exe, main_p, loss, monitor, rng, n=3):
    import numpy as np
    vitals = []
    for _ in range(n):
        feed = {"img": rng.rand(16, 784).astype("float32"),
                "label": rng.randint(0, 10, (16, 1)).astype("int64")}
        out = exe.run(main_p, feed=feed,
                      fetch_list=[loss] + monitor.fetch_list)
        monitor.observe_fetches(out[1:], loss=out[0])
        vitals.append([float(np.ravel(o)[0]) for o in out])
    return vitals


def run_demo(selftest=False):
    """Returns (problems, info). problems == [] means healthy."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import diagnostics as dg

    problems = []
    info = {}

    def check(ok, what):
        if not ok:
            problems.append(what)
        return ok

    # 0) diagnostics OFF must take zero snapshots / records
    dg.recorder.disable()
    main_p, startup_p, loss, opt = _build_mnist()
    exe = pt.Executor(pt.CPUPlace())
    scope = pt.Scope()
    rng = np.random.RandomState(0)
    with pt.scope_guard(scope):
        exe.run(startup_p)
        feed = {"img": rng.rand(16, 784).astype("float32"),
                "label": rng.randint(0, 10, (16, 1)).astype("int64")}
        exe.run(main_p, feed=feed, fetch_list=[loss])
    check(exe.diag_snapshot_count == 0,
          "diagnostics-off run took a pre-step snapshot")

    # 1) arm the flight recorder, run healthy steps with the monitor
    out_dir = tempfile.mkdtemp(prefix="tpudoctor_")
    rec = dg.recorder.enable(out_dir, capacity=64, install_hooks=False)
    monitor = opt.health_monitor
    with pt.scope_guard(scope):
        vitals = _healthy_steps(exe, main_p, loss, monitor, rng)
        info["healthy_vitals"] = vitals
        gnorms = [v[1] for v in vitals]
        check(all(np.isfinite(g) and g > 0 for g in gnorms),
              f"healthy grad norms not positive/finite: {gnorms}")
        check(not monitor.warnings,
              f"healthy steps fired warnings: {monitor.warnings}")

        # 2) inject: a feed that overflows the first fc matmul
        block = main_p.global_block()
        expect_idx = next(i for i, op in enumerate(block.ops)
                          if op.type == "mul")
        bad_feed = {"img": np.full((16, 784), 3e38, "float32"),
                    "label": np.zeros((16, 1), "int64")}
        report = None
        try:
            exe.run(main_p, feed=bad_feed, fetch_list=[loss],
                    check_nan_inf=True)
            problems.append("injected overflow raised no NanInfError")
        except dg.NanInfError as e:
            report = e.report
        except FloatingPointError as e:
            problems.append(f"raised plain FloatingPointError: {e}")
    if report is not None:
        info["culprit"] = {"phase": report.phase,
                           "op_type": report.op_type,
                           "block_idx": report.block_idx,
                           "op_idx": report.op_idx,
                           "hint": report.hint}
        check(report.phase == "forward",
              f"phase {report.phase!r} != 'forward'")
        check(report.op_type == "mul",
              f"culprit op type {report.op_type!r} != 'mul'")
        check(report.op_idx == expect_idx,
              f"culprit op idx {report.op_idx} != {expect_idx}")
        check(bool(report.input_stats) and bool(report.output_stats),
              "report missing tensor stats")
        check(bool(report.feed_fingerprint), "report missing feed "
              "fingerprint")
        check(bool(report.hint), "report missing fix hint")
        check(report.step is not None
              and report.program_version is not None,
              "report missing step/program fingerprint")
        if not selftest:
            print(report.format())
            print()

    # 3) the failure dumped the flight recorder; round-trip it
    dump_path = rec.last_dump_path
    info["dump"] = dump_path
    if check(dump_path is not None and os.path.exists(dump_path or ""),
             "no flight-recorder dump written on NaN"):
        with open(dump_path) as f:
            payload = json.load(f)
        check(payload.get("reason") == "nan_inf",
              f"dump reason {payload.get('reason')!r} != 'nan_inf'")
        check(len(payload.get("records", [])) >= 3,
              "dump lost the healthy-step records")
        check((payload.get("report") or {}).get("op_type") == "mul",
              "dump's attached report lost the culprit")
        text = format_dump(payload)
        check("nan_inf" in text and "mul" in text
              and "grad_norm" in text,
              "postmortem printer lost dump content")
        if not selftest:
            print(text)
    dg.recorder.disable()
    return problems, info


# ------------------------------------------------------------------ main

def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("command", nargs="?", default="demo",
                   choices=["demo", "postmortem"])
    p.add_argument("path", nargs="?", default=None,
                   help="dump file for postmortem")
    p.add_argument("--selftest", action="store_true",
                   help="run the CI gate assertions")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="one machine-readable JSON verdict line")
    p.add_argument("--platform", default="cpu",
                   help="JAX_PLATFORMS to force ('env' keeps the "
                        "environment's; default cpu so the CLI never "
                        "hangs on a down relay)")
    args = p.parse_args(argv)

    if args.command == "postmortem":
        if not args.path:
            p.error("postmortem needs a dump path")
        return cmd_postmortem(args.path)

    if args.platform != "env":
        os.environ["JAX_PLATFORMS"] = args.platform

    problems, info = run_demo(selftest=args.selftest)
    result = {"ok": not problems, "problems": problems}
    result.update(info)
    if args.as_json:
        print(json.dumps(result, default=str))
    else:
        if problems:
            for prob in problems:
                print(f"PROBLEM: {prob}", file=sys.stderr)
        else:
            print("tpudoctor: all checks passed "
                  f"(culprit {info.get('culprit')})")
    return 2 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
