"""Benchmark entry — prints ONE JSON line with the headline metric.

Flagship: Transformer train-step throughput (tokens/sec) on the real
chip — the BASELINE.json "Transformer-base NMT" config, sized to the
single v5e chip the driver provides.
"""
import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core.trace import build_step_fn
    from paddle_tpu.models import transformer as tfm

    B, T = 64, 128     # 64 saturates the MXU better than 32 (measured)
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            cfg = tfm.TransformerConfig(
                src_vocab=8000, trg_vocab=8000, max_len=T,
                d_model=512, d_inner=2048, n_head=8, n_layer=6,
                dropout=0.1)
            feeds, avg_cost, tok = tfm.build_program(cfg, maxlen=T)
            pt.optimizer.Adam(1e-3).minimize(avg_cost)
    # bf16 matmuls on the MXU, fp32 optimizer state (SURVEY §5: bf16 target)
    pt.amp.cast_program_to_bf16(main_p)

    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        pt.amp.cast_params_to_bf16(main_p, scope)
        persist = {v.name: scope.get(v.name)
                   for v in main_p.persistable_vars()}

    rng = np.random.RandomState(0)
    src = rng.randint(3, cfg.src_vocab, (B, T)).astype("int32")
    trg = np.concatenate([np.zeros((B, 1), "int32"),
                          (src[:, :-1] + 1) % cfg.trg_vocab], axis=1)
    feed = {"src": jnp.asarray(src),
            "src_len": jnp.full(B, T, jnp.int32),
            "trg": jnp.asarray(trg),
            "trg_len": jnp.full(B, T, jnp.int32),
            "label": jnp.asarray((src + 1) % cfg.trg_vocab, jnp.int32)}
    key = jax.random.PRNGKey(0)

    step_fn = build_step_fn(main_p, [avg_cost.name], False, None)
    jfn = jax.jit(step_fn, donate_argnums=(0,))
    fetches, persist = jfn(persist, feed, key)
    # block_until_ready does not synchronize through the axon relay; a
    # device→host readback is the only reliable completion barrier.
    np.asarray(fetches[0])

    n = 50
    t0 = time.perf_counter()
    for _ in range(n):
        fetches, persist = jfn(persist, feed, key)
    loss = float(np.asarray(fetches[0]))
    dt = time.perf_counter() - t0
    assert np.isfinite(loss), f"non-finite loss {loss}"
    tokens_per_sec = n * B * T / dt

    baseline = None
    try:
        with open("BASELINE.json") as f:
            baseline = json.load(f).get("published", {}).get(
                "transformer_tokens_per_sec")
    except Exception:
        pass
    vs = tokens_per_sec / baseline if baseline else 1.0
    print(json.dumps({
        "metric": "transformer_base_train_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
