"""Benchmark entry — prints ONE JSON line with the headline metric.

Flagship: Transformer-base train-step throughput (tokens/sec) on the
real chip (ref benchmark/fluid/machine_translation.py), with MFU
computed from XLA's own cost analysis (fallback: analytic matmul FLOPs)
and corroborated by device-side profiler timing. Secondary metrics
(SURVEY §5): ResNet-50 images/sec, MNIST MLP steps/sec, inference
latency — all in the same JSON line.

Process structure: the axon TPU relay hangs (not errors) during init
when it is down, and outages exceed an hour, so the parent process
NEVER touches the TPU itself. It probes in subprocesses with backoff,
then runs the whole TPU benchmark in a supervised child with a hard
timeout, retrying while the budget (BENCH_TOTAL_BUDGET_S, default 45
min) lasts; only then does it fall back to a CPU run. Never exits
without a JSON line: on failure prints
{"metric": ..., "value": 0, "error": ..., "stage": ...}.
"""
import json
import sys
import time
import traceback

import numpy as np

_STAGE = {"stage": "import"}


def _emit(obj):
    print(json.dumps(obj))
    sys.stdout.flush()


# Peak bf16 FLOP/s per chip by device kind (scaling-book table).
_PEAK_BF16 = (
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5litepod", 197e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12), ("v2", 45e12),
)


def _peak_flops(device):
    kind = getattr(device, "device_kind", "").lower()
    for tag, peak in _PEAK_BF16:
        if tag in kind:
            return peak
    if device.platform in ("tpu", "axon"):
        return 197e12  # conservative default: v5e
    return None


def _probe_tpu(timeout=120.0):
    """Probe the default backend in a SUBPROCESS with a hard timeout —
    the axon TPU plugin can hang (not error) during init, and a hung
    jax.devices() in this process would be unrecoverable. Returns the
    probed platform string, or None on hang/failure."""
    import subprocess
    # a full compute+readback, not just device listing: the relay has
    # been observed to answer jax.devices() while hanging on any real
    # dispatch, and a listing-only probe would green-light a child run
    # that then burns its whole timeout
    code = ("import jax, jax.numpy as jnp, numpy as np; "
            "d = jax.devices(); x = jnp.ones((8, 8)); "
            "assert float(np.asarray(x + x)[0, 0]) == 2.0; "
            "print('PLATFORM=' + d[0].platform)")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return None
    if p.returncode != 0:
        return None
    for line in (p.stdout or "").splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1].strip()
    return None


def _force_cpu():
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform


def _aot_compile(jfn, args):
    """AOT-compile once; return (callable, flops) — the compiled
    executable IS the benchmarked callable, so cost analysis costs no
    second compile."""
    flops = None
    try:
        compiled = jfn.lower(*args).compile()
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            f = ca.get("flops")
            flops = float(f) if f and f > 0 else None
        except Exception:
            pass
        return compiled, flops
    except Exception:
        return jfn, None


def _median_window_time(run_window, windows):
    """Median wall time of `windows` repeats of run_window() — the relay
    adds ±5-20% noise run to run; the median is an honest de-noised
    estimate (not a peak)."""
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        run_window()
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def _transformer_analytic_flops(cfg, B, T):
    """Analytic matmul FLOPs per train step (fwd 2MNK, bwd 4MNK → 6MNK)."""
    d, dff, L = cfg.d_model, cfg.d_inner, cfg.n_layer
    # per token per layer: qkv+o (4 d*d) + ffn (2 d*dff); encoder+decoder
    # decoder adds cross-attn qkv+o (~4 d*d more)
    enc = L * (4 * d * d + 2 * d * dff)
    dec = L * (8 * d * d + 2 * d * dff)
    attn = 2 * L * 2 * (2 * T * d)  # scores+context, enc+dec, per token
    logits = cfg.trg_vocab * d
    per_token = 2 * (enc + dec + attn + logits)
    return 6 / 2 * per_token * B * T  # 3x fwd-only for fwd+bwd


def bench_transformer(platform):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core.trace import build_step_fn
    from paddle_tpu.models import transformer as tfm

    on_tpu = platform in ("tpu", "axon")
    B, T = (64, 128) if on_tpu else (8, 32)
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            cfg = tfm.TransformerConfig(
                src_vocab=8000, trg_vocab=8000, max_len=T,
                d_model=512, d_inner=2048, n_head=8, n_layer=6,
                dropout=0.1)
            feeds, avg_cost, tok = tfm.build_program(cfg, maxlen=T)
            pt.optimizer.Adam(1e-3).minimize(avg_cost)
    # bf16 matmuls on the MXU, fp32 optimizer state (SURVEY §5 target)
    pt.amp.cast_program_to_bf16(main_p)

    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        pt.amp.cast_params_to_bf16(main_p, scope)
        persist = {v.name: scope.get(v.name)
                   for v in main_p.persistable_vars()}

    rng = np.random.RandomState(0)
    src = rng.randint(3, cfg.src_vocab, (B, T)).astype("int32")
    trg = np.concatenate([np.zeros((B, 1), "int32"),
                          (src[:, :-1] + 1) % cfg.trg_vocab], axis=1)
    feed = {"src": jnp.asarray(src),
            "src_len": jnp.full(B, T, jnp.int32),
            "trg": jnp.asarray(trg),
            "trg_len": jnp.full(B, T, jnp.int32),
            "label": jnp.asarray((src + 1) % cfg.trg_vocab, jnp.int32)}
    key = jax.random.PRNGKey(0)

    step_fn = build_step_fn(main_p, [avg_cost.name], False, None)
    jfn, flops_ca = _aot_compile(jax.jit(step_fn, donate_argnums=(0,)),
                                 (persist, feed, key))
    flops_step = flops_ca or _transformer_analytic_flops(cfg, B, T)
    fetches, persist = jfn(persist, feed, key)
    # block_until_ready does not synchronize through the axon relay; a
    # device→host readback is the only reliable completion barrier.
    np.asarray(fetches[0])

    n = 50 if on_tpu else 5
    state = {"persist": persist, "loss": 0.0}

    def window():
        p = state["persist"]
        for _ in range(n):
            fetches, p = jfn(p, feed, key)
        state["persist"] = p
        state["loss"] = float(np.asarray(fetches[0]))

    dt = _median_window_time(window, 3 if on_tpu else 1)
    loss = state["loss"]
    assert np.isfinite(loss), f"non-finite loss {loss}"
    tokens_per_sec = n * B * T / dt

    peak = _peak_flops(jax.devices()[0])
    mfu = (flops_step * n / dt / peak) if peak else None
    evidence = {
        "mfu_method": "xla_cost_analysis" if flops_ca
                      else "analytic_matmul",
        "flops_per_step": flops_step,
        "wall_step_ms": round(dt / n * 1e3, 2),
    }
    if on_tpu:
        # device-side per-step time from the profiler trace — wall
        # clock through the relay carries ±5-20% noise; the xplane
        # event durations are the corroborating record
        try:
            from paddle_tpu.profiler import profile_step_fn

            def one_step():
                fetches, state["persist"] = jfn(state["persist"], feed,
                                                key)
                return fetches

            dev_s, fams = profile_step_fn(one_step, steps=10)
            evidence["device_step_ms"] = round(dev_s * 1e3, 2)
            evidence["device_mfu"] = round(flops_step / dev_s / peak, 4)
            top = sorted(fams.items(), key=lambda kv: -kv[1])[:5]
            evidence["device_top_ops_ms"] = {
                k: round(v * 1e3, 2) for k, v in top}
        except Exception as e:
            evidence["device_profile_error"] = f"{type(e).__name__}: {e}"
    return tokens_per_sec, mfu, loss, evidence


def bench_resnet(platform):
    """ResNet-50 train-step images/sec (ref benchmark/fluid/models/resnet.py)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core.trace import build_step_fn
    from paddle_tpu.models import resnet

    on_tpu = platform in ("tpu", "axon")
    # B=128 measured +18% img/s over B=32 on v5e (better conv batching)
    B, HW = (128, 224) if on_tpu else (4, 64)
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            img = pt.layers.data("image", (3, HW, HW), dtype="float32")
            lbl = pt.layers.data("label", (1,), dtype="int64")
            predict = resnet.resnet(img, class_dim=1000, depth=50)
            loss = pt.layers.mean(pt.layers.cross_entropy(
                input=predict, label=lbl))
            pt.optimizer.Momentum(0.1, 0.9).minimize(loss)
    pt.amp.cast_program_to_bf16(main_p)

    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        pt.amp.cast_params_to_bf16(main_p, scope)
        persist = {v.name: scope.get(v.name)
                   for v in main_p.persistable_vars()}

    rng = np.random.RandomState(0)
    feed = {"image": jnp.asarray(rng.rand(B, 3, HW, HW).astype("float32")),
            "label": jnp.asarray(rng.randint(0, 1000, (B, 1)), jnp.int32)}
    key = jax.random.PRNGKey(0)
    step_fn = build_step_fn(main_p, [loss.name], False, None)
    jfn = jax.jit(step_fn, donate_argnums=(0,))
    fetches, persist = jfn(persist, feed, key)
    np.asarray(fetches[0])
    n = 20 if on_tpu else 2
    state = {"persist": persist, "loss": 0.0}

    def window():
        p = state["persist"]
        for _ in range(n):
            fetches, p = jfn(p, feed, key)
        state["persist"] = p
        state["loss"] = float(np.asarray(fetches[0]))

    dt = _median_window_time(window, 3 if on_tpu else 1)
    assert np.isfinite(state["loss"])
    return n * B / dt


def bench_flash_long_context(platform):
    """Long-context flash attention: causal fwd+bwd at T=32k (the
    unfused path cannot compile here — SURVEY §5 long-context story)."""
    if platform not in ("tpu", "axon"):
        return None
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa
    B, H, T, D = 1, 8, 32768, 64
    rng = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rng.randn(B, H, T, D).astype("float32"),
                           jnp.bfloat16) for _ in range(3)]

    def loss_fn(q, k, v):
        out = fa.flash_attention(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)))
    out = g(q, k, v)
    np.asarray(out[0][0, 0, 0])
    n = 5

    def window():
        out = g(q, k, v)
        for _ in range(n - 1):
            out = g(q, k, v)
        np.asarray(out[0][0, 0, 0])

    dt = _median_window_time(window, 3) / n
    # causal fwd+bwd matmul flops: 3 passes * 2MNK * BHT^2D / 2
    fl = 12 * B * H * T * T * D * 0.5
    peak = _peak_flops(jax.devices()[0])
    return {"flash_attn_32k_causal_ms": round(dt * 1e3, 1),
            "flash_attn_32k_mfu": round(fl / dt / peak, 4)}


def bench_inference(platform):
    """InferenceEngine latency/throughput (ref inference/api/api_impl.cc
    deploy story): transformer encoder forward and ResNet-50 forward,
    jit-cached path plus the AOT-compiled (save_compiled/load_compiled)
    path for ResNet."""
    import tempfile
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.models import resnet

    on_tpu = platform in ("tpu", "axon")
    out = {}
    rng = np.random.RandomState(0)

    # --- ResNet-50 forward, B=32 ---
    B, HW = (32, 224) if on_tpu else (2, 64)
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            img = pt.layers.data("image", (3, HW, HW), dtype="float32")
            predict = resnet.resnet(img, class_dim=1000, depth=50)
    infer_p = main_p.clone(for_test=True)
    scope = pt.Scope()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
    eng = InferenceEngine(infer_p, ["image"], [predict], scope,
                          use_bf16=True)
    x = rng.rand(B, 3, HW, HW).astype("float32")
    eng.run({"image": x})  # compile
    n = 20 if on_tpu else 2
    dt = _median_window_time(
        lambda: [eng.run({"image": x}, return_numpy=False)
                 for _ in range(n)] and np.asarray(
            eng.run({"image": x})[0][0, :1]), 3) / (n + 1)
    out["resnet50_infer_images_per_sec"] = round(B / dt, 1)
    out["resnet50_infer_latency_ms"] = round(dt * 1e3, 2)

    # AOT roundtrip: save_compiled → load_compiled → run. TPU only:
    # exporting ResNet-50 StableHLO on CPU takes minutes and the CPU
    # number means nothing (the roundtrip itself is covered by tests)
    if not on_tpu:
        return out
    try:
        with tempfile.TemporaryDirectory() as d:
            eng.save_compiled(d, {"image": (B, 3, HW, HW)})
            pred = InferenceEngine.load_compiled(d)
            pred.run({"image": x})
            dt = _median_window_time(
                lambda: np.asarray(pred.run({"image": x})[0][0, :1]), 3)
            out["resnet50_infer_aot_latency_ms"] = round(dt * 1e3, 2)
    except Exception as e:
        out["resnet50_infer_aot_error"] = f"{type(e).__name__}: {e}"
    return out


def bench_mnist(platform):
    """MNIST MLP train steps/sec (ref benchmark/fluid/mnist.py)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core.trace import build_step_fn
    from paddle_tpu.models import mnist as mn

    B = 128
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            img = pt.layers.data("image", (784,), dtype="float32")
            lbl = pt.layers.data("label", (1,), dtype="int64")
            predict = mn.mlp(img)
            loss = pt.layers.mean(pt.layers.cross_entropy(
                input=predict, label=lbl))
            pt.optimizer.Adam(1e-3).minimize(loss)
    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        persist = {v.name: scope.get(v.name)
                   for v in main_p.persistable_vars()}
    rng = np.random.RandomState(0)
    feed = {"image": jnp.asarray(rng.rand(B, 784).astype("float32")),
            "label": jnp.asarray(rng.randint(0, 10, (B, 1)), jnp.int32)}
    key = jax.random.PRNGKey(0)
    step_fn = build_step_fn(main_p, [loss.name], False, None)
    jfn = jax.jit(step_fn, donate_argnums=(0,))
    fetches, persist = jfn(persist, feed, key)
    np.asarray(fetches[0])
    n = 200
    state = {"persist": persist}

    def window():
        p = state["persist"]
        for _ in range(n):
            fetches, p = jfn(p, feed, key)
        state["persist"] = p
        np.asarray(fetches[0])

    dt = _median_window_time(window, 3)
    return n / dt


def run_benchmarks(platform):
    """Run every benchmark on the already-initialized backend; returns
    the result dict (no emission — the caller owns the single line)."""
    import jax
    result = {
        "metric": "transformer_base_train_tokens_per_sec",
        "value": 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
    }
    try:
        result["platform"] = platform
        result["device_kind"] = getattr(jax.devices()[0],
                                        "device_kind", "")

        _STAGE["stage"] = "transformer"
        tokens_per_sec, mfu, loss, evidence = bench_transformer(platform)
        result["value"] = round(tokens_per_sec, 1)
        if mfu is not None:
            result["mfu"] = round(mfu, 4)
        result["loss"] = round(loss, 4)
        result["evidence"] = evidence

        baseline = None
        try:
            import os
            bp = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BASELINE.json")
            with open(bp) as f:
                baseline = json.load(f).get("published", {}).get(
                    "transformer_tokens_per_sec")
        except Exception:
            pass
        if baseline:
            ratio = tokens_per_sec / baseline
            # keep small CPU-fallback ratios visible (0.0002, not 0.0)
            result["vs_baseline"] = float(f"{ratio:.3g}")
        else:
            result["vs_baseline"] = 1.0

        for name, fn in (("resnet50_images_per_sec", bench_resnet),
                         ("mnist_mlp_steps_per_sec", bench_mnist)):
            _STAGE["stage"] = name
            try:
                result[name] = round(fn(platform), 1)
            except Exception as e:
                result[name + "_error"] = f"{type(e).__name__}: {e}"
        _STAGE["stage"] = "inference"
        try:
            result.update(bench_inference(platform))
        except Exception as e:
            result["inference_error"] = f"{type(e).__name__}: {e}"
        _STAGE["stage"] = "flash_long_context"
        try:
            extra = bench_flash_long_context(platform)
            if extra:
                result.update(extra)
        except Exception as e:
            result["flash_long_context_error"] = f"{type(e).__name__}: {e}"
    except Exception as e:
        result["error"] = f"{type(e).__name__}: {e}"
        result["stage"] = _STAGE["stage"]
        result["traceback"] = traceback.format_exc()[-1500:]
    return result


def _child_main():
    """BENCH_CHILD=1 mode: assume the default (TPU) backend, run all
    benchmarks, print the JSON line. Any hang here is the parent's
    problem — it holds the kill timer."""
    import jax
    platform = jax.devices()[0].platform  # may hang; parent supervises
    _emit(run_benchmarks(platform))


def _supervise():
    """Parent mode: never touches the TPU in-process. Probe with
    backoff, then run the TPU benchmark in a killable child; retry
    until BENCH_TOTAL_BUDGET_S is spent, then CPU fallback."""
    import os
    import subprocess
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "2700"))
    child_timeout = float(os.environ.get("BENCH_CHILD_TIMEOUT_S", "1500"))
    t0 = time.monotonic()
    remaining = lambda: budget - (time.monotonic() - t0)
    attempts, runs, last_err = 0, 0, ""
    delay = 10.0

    def backoff():
        nonlocal delay
        time.sleep(min(delay, max(0.0, remaining() - 60.0)))
        delay = min(delay * 2, 180.0)

    while remaining() > 60.0 and runs < 5:
        attempts += 1
        platform = _probe_tpu(timeout=min(120.0, remaining()))
        if platform is None:
            last_err = "probe timeout/failure"
            backoff()
            continue
        if platform not in ("tpu", "axon"):
            # no TPU in this environment at all (e.g. CPU-only CI):
            # don't burn the budget retrying
            break
        # relay reachable — run the real benchmark in a killable child
        runs += 1
        env = dict(os.environ, BENCH_CHILD="1")
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                timeout=min(child_timeout, max(remaining(), 5.0)))
        except subprocess.TimeoutExpired:
            last_err = f"child run {runs} hung (killed)"
            backoff()
            continue
        line = next((l for l in reversed(
            (p.stdout or "").strip().splitlines())
            if l.startswith("{")), None)
        if p.returncode == 0 and line:
            try:
                result = json.loads(line)
            except Exception:
                last_err = f"child run {runs} emitted invalid JSON"
                backoff()
                continue
            if result.get("platform") in ("tpu", "axon") \
                    and not result.get("error"):
                result["probe"] = {
                    "attempts": attempts, "child_runs": runs,
                    "seconds": round(time.monotonic() - t0, 1)}
                _emit(result)
                return
            last_err = (f"child run {runs}: platform="
                        f"{result.get('platform')} "
                        f"error={result.get('error')!r}")
        else:
            last_err = (f"child run {runs} rc={p.returncode}: "
                        + (p.stderr or "")[-300:].replace("\n", " "))
        # failed child runs back off too — each retry pays full TPU
        # init, and a deterministic child bug would otherwise spin
        backoff()
    # budget exhausted — honest CPU fallback in-process
    platform = _force_cpu()
    result = run_benchmarks(platform)
    result["probe"] = {"attempts": attempts, "child_runs": runs,
                      "seconds": round(time.monotonic() - t0, 1),
                      "tpu_unreachable": last_err}
    _emit(result)


def main():
    import os
    if os.environ.get("BENCH_CHILD"):
        _child_main()
    else:
        _supervise()


if __name__ == "__main__":
    main()
