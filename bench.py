"""Benchmark entry — prints ONE JSON line with the headline metric.

Flagship: Transformer-base train-step throughput (tokens/sec) on the
real chip (ref benchmark/fluid/machine_translation.py), with MFU
computed from XLA's own cost analysis (fallback: analytic matmul FLOPs)
and corroborated by device-side profiler timing. Secondary metrics
(SURVEY §5): ResNet-50 images/sec, MNIST MLP steps/sec, inference
latency — all in the same JSON line.

Process structure: the axon TPU relay hangs (not errors) during init
when it is down, so the parent process NEVER touches the TPU itself.
The parent is built so its failure mode can never be silence (the
round-3 artifact was rc=124 with EMPTY output — a driver timeout
killed the old design before it printed anything):

 1. a bootstrap JSON line is emitted at t=0, before any backend work;
 2. benchmark children stream a fresh JSON line after EVERY completed
    sub-benchmark, and the parent re-emits each improvement
    immediately — the driver records the LAST stdout line, so a kill
    at any moment still leaves the best result so far on record;
 3. SIGTERM/SIGINT re-emit the best-known line and exit;
 4. the whole budget (BENCH_TOTAL_BUDGET_S) defaults to 8 minutes so
    a full run fits inside any plausible driver timeout.

Probe plan (staged, every attempt recorded in the artifact's
"probe.attempts" trail with rc + stdout/stderr tails — even on
timeout, so a miss is diagnosable): (A) default-env compute probe in
a killable subprocess → TPU child on success; on timeout a
listing-only probe localizes WHERE init hung via stage markers
(IMPORTING/IMPORTED/DEVICES=/COMPUTE_OK); (B) supervised CPU child so
a result line always exists; (C) escalated re-probe with explicit
JAX_PLATFORMS=axon; (D) a last default-env probe with the remaining
budget. A line with platform "tpu"/"axon" and value>0 always beats a
CPU line, which beats the bootstrap stub.
"""
import json
import os
import sys
import time
import traceback

import numpy as np

_STAGE = {"stage": "import"}


_EMIT_LOCK = __import__("threading").Lock()


def _emit(obj, lead=""):
    """ONE atomic write per line: the pump threads and the SIGTERM
    handler both emit, and an interleaved payload/newline pair would
    corrupt the guaranteed-parseable last line."""
    with _EMIT_LOCK:
        sys.stdout.write(lead + json.dumps(obj) + "\n")
        sys.stdout.flush()


def _score(obj):
    """Rank result lines: witnessed-TPU > any-result > stub."""
    if not obj:
        return -1
    has_value = obj.get("value", 0) and obj["value"] > 0
    if obj.get("platform") in ("tpu", "axon") and has_value:
        return 2
    return 1 if has_value else 0


# The driver's tail capture records the LAST stdout line; round-5
# VERDICT showed an embedded probe trail blowing past it (parsed:
# null). The final line must stay under this budget — the full
# forensic trail goes to the BENCH_probe.json artifact instead.
_FINAL_LINE_BUDGET = 2048


def _compact_final(obj, limit=_FINAL_LINE_BUDGET):
    """Shrink a result line under `limit` bytes by dropping forensic
    bulk (largest first), never the headline schema keys."""
    obj = dict(obj)
    if isinstance(obj.get("probe"), dict):
        obj["probe"] = dict(obj["probe"])
    fits = lambda: len(json.dumps(obj)) < limit
    if fits():
        return obj
    for key in ("traceback", "attempts", "children", "evidence",
                "stage_seconds", "device_profile"):
        obj.pop(key, None)
        if isinstance(obj.get("probe"), dict):
            obj["probe"].pop(key, None)
        if fits():
            return obj
    keep = {"metric", "value", "unit", "vs_baseline", "platform",
            "probe", "mnist_mlp_steps_per_sec", "error", "signal"}
    for key in sorted(obj, key=lambda k: -len(json.dumps(obj[k],
                                                         default=str))):
        if key in keep:
            continue
        obj.pop(key, None)
        if fits():
            return obj
    return obj


# Peak bf16 FLOP/s per chip by device kind (scaling-book table).
_PEAK_BF16 = (
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5litepod", 197e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12), ("v2", 45e12),
)


def _peak_flops(device):
    # the runtime attribution layer owns the peak table now (including
    # the PADDLE_TPU_PEAK_FLOPS override), so bench's offline MFU and
    # the live perf.mfu gauge read the same denominator; the local
    # table stays as fallback for a stripped install
    try:
        from paddle_tpu.telemetry.attribution import peak_flops
        return peak_flops(device)
    except Exception:
        pass
    kind = getattr(device, "device_kind", "").lower()
    for tag, peak in _PEAK_BF16:
        if tag in kind:
            return peak
    if device.platform in ("tpu", "axon"):
        return 197e12  # conservative default: v5e
    return None


def _text_tail(blob, n=400):
    """Decode a subprocess output fragment (bytes/str/None — on
    TimeoutExpired CPython attaches the partial output as BYTES even
    in text mode) and keep the last n chars, newline-flattened."""
    if blob is None:
        return ""
    if isinstance(blob, bytes):
        blob = blob.decode("utf-8", "replace")
    return blob[-n:].replace("\n", " | ").strip()


# Probe stage markers: the probe child prints one marker per phase so a
# timeout's partial stdout pinpoints WHERE init died (round-4 verdict:
# "impossible to tell a dead relay from a hung plugin init").
_PROBE_CODE = {
    # device listing only — distinguishes "plugin absent / errors out"
    # (fast rc!=0) from "client init hangs" (timeout with IMPORTED
    # marker but no DEVICES line)
    "list": (
        "import sys; print('IMPORTING', flush=True); "
        "import jax; print('IMPORTED', flush=True); "
        "d = jax.devices(); "
        "print('DEVICES=' + ';'.join(x.platform + '/' + "
        "str(getattr(x, 'device_kind', '?')) for x in d), flush=True); "
        "print('PLATFORM=' + d[0].platform, flush=True)"),
    # C-level PJRT probe through the native predictor: dlopen the axon
    # plugin directly, pass the SAME NamedValue session options the jax
    # registration carries, and call PJRT_Client_Create from C — if
    # this hangs/errors where jax also hangs, the stall is proven to be
    # relay-side (below jax); if it succeeds, the fault is in the jax
    # layer. Pure diagnosis; never gates a benchmark child.
    "cprobe": (
        "import json, os; print('IMPORTING', flush=True); "
        "import jax; from jax._src import xla_bridge as xb; "
        "print('IMPORTED', flush=True); "
        "fac = xb._backend_factories.get('axon'); "
        "opts = getattr(getattr(fac, 'factory', None), 'keywords', {})"
        ".get('options', {}) if fac else {}; "
        "os.environ['PTPU_PJRT_CREATE_OPTIONS'] = "
        "';'.join(f'{k}={v}' for k, v in opts.items()); "
        "print('OPTIONS_SET=' + str(sorted(opts)), flush=True); "
        "from paddle_tpu.native import predictor as _np; "
        "plug = _np.find_plugin(); "
        "print('PLUGIN=' + str(plug), flush=True); "
        "r = _np.probe(plug) if plug else None; "
        "print('CPROBE=' + json.dumps(r), flush=True); "
        "print('PLATFORM=none', flush=True)"),
    # full compute+readback — the relay has been observed to answer
    # jax.devices() while hanging on any real dispatch, so only this
    # green-lights a benchmark child
    "compute": (
        "import sys; print('IMPORTING', flush=True); "
        "import jax, jax.numpy as jnp, numpy as np; "
        "print('IMPORTED', flush=True); "
        "d = jax.devices(); "
        "print('DEVICES=' + ';'.join(x.platform + '/' + "
        "str(getattr(x, 'device_kind', '?')) for x in d), flush=True); "
        "x = jnp.ones((8, 8)); "
        "assert float(np.asarray(x + x)[0, 0]) == 2.0; "
        "print('COMPUTE_OK', flush=True); "
        "print('PLATFORM=' + d[0].platform, flush=True)"),
}


def _probe_tpu(timeout=120.0, mode="compute", platforms=None):
    """Probe the backend in a SUBPROCESS with a hard timeout — the axon
    TPU plugin can hang (not error) during init, and a hung
    jax.devices() in this process would be unrecoverable.

    Returns a dict recording the attempt in full (the round-4 artifact
    threw the evidence away and its miss was undiagnosable):
      {mode, platforms, timeout, seconds, outcome, platform,
       rc, stdout_tail, stderr_tail}
    outcome: "ok" (platform answered) | "timeout" | "error".
    The partial stdout of a timed-out child still carries the stage
    markers (IMPORTING/IMPORTED/DEVICES=/COMPUTE_OK), so the trail
    shows exactly which phase hung.
    """
    import subprocess
    env = dict(os.environ)
    if platforms:
        env["JAX_PLATFORMS"] = platforms
    rec = {"mode": mode, "platforms": platforms or "(default)",
           "timeout": round(timeout, 1)}
    t0 = time.monotonic()
    try:
        p = subprocess.run([sys.executable, "-c", _PROBE_CODE[mode]],
                           capture_output=True, text=True,
                           timeout=timeout, env=env)
        rec.update(rc=p.returncode,
                   stdout_tail=_text_tail(p.stdout),
                   stderr_tail=_text_tail(p.stderr))
        platform = None
        for line in (p.stdout or "").splitlines():
            if line.startswith("PLATFORM="):
                platform = line.split("=", 1)[1].strip()
        if p.returncode == 0 and platform:
            rec.update(outcome="ok", platform=platform)
        else:
            rec.update(outcome="error", platform=platform)
    except subprocess.TimeoutExpired as e:
        rec.update(outcome="timeout", platform=None, rc=None,
                   stdout_tail=_text_tail(e.stdout),
                   stderr_tail=_text_tail(e.stderr))
    except Exception as e:  # never let the probe kill the supervisor
        rec.update(outcome="error", platform=None, rc=None,
                   stdout_tail="", stderr_tail=_text_tail(repr(e)))
    rec["seconds"] = round(time.monotonic() - t0, 1)
    return rec


def _aot_compile(jfn, args):
    """AOT-compile once; return (callable, flops) — the compiled
    executable IS the benchmarked callable, so cost analysis costs no
    second compile."""
    flops = None
    try:
        compiled = jfn.lower(*args).compile()
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            f = ca.get("flops")
            flops = float(f) if f and f > 0 else None
        except Exception:
            pass
        return compiled, flops
    except Exception:
        return jfn, None


def _median_window_time(run_window, windows):
    """Median wall time of `windows` repeats of run_window() — the relay
    adds ±5-20% noise run to run; the median is an honest de-noised
    estimate (not a peak)."""
    times = []
    for _ in range(windows):
        t0 = time.perf_counter()
        run_window()
        times.append(time.perf_counter() - t0)
    return sorted(times)[len(times) // 2]


def _transformer_analytic_flops(cfg, B, T):
    """Analytic matmul FLOPs per train step (fwd 2MNK, bwd 4MNK → 6MNK)."""
    d, dff, L = cfg.d_model, cfg.d_inner, cfg.n_layer
    # per token per layer: qkv+o (4 d*d) + ffn (2 d*dff); encoder+decoder
    # decoder adds cross-attn qkv+o (~4 d*d more)
    enc = L * (4 * d * d + 2 * d * dff)
    dec = L * (8 * d * d + 2 * d * dff)
    attn = 2 * L * 2 * (2 * T * d)  # scores+context, enc+dec, per token
    logits = cfg.trg_vocab * d
    per_token = 2 * (enc + dec + attn + logits)
    return 6 / 2 * per_token * B * T  # 3x fwd-only for fwd+bwd


def bench_transformer(platform, batch=None, profile=True):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core.trace import build_step_fn
    from paddle_tpu.models import transformer as tfm

    on_tpu = platform in ("tpu", "axon")
    B, T = (64, 128) if on_tpu else (8, 32)
    if batch:
        B = batch
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            cfg = tfm.TransformerConfig(
                src_vocab=8000, trg_vocab=8000, max_len=T,
                d_model=512, d_inner=2048, n_head=8, n_layer=6,
                dropout=0.1, fused_qkv=True)
            feeds, avg_cost, tok = tfm.build_program(cfg, maxlen=T)
            pt.optimizer.Adam(1e-3).minimize(avg_cost)
    # bf16 matmuls on the MXU, fp32 optimizer state (SURVEY §5 target)
    pt.amp.cast_program_to_bf16(main_p)

    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        pt.amp.cast_params_to_bf16(main_p, scope)
        persist = {v.name: scope.get(v.name)
                   for v in main_p.persistable_vars()}

    rng = np.random.RandomState(0)
    src = rng.randint(3, cfg.src_vocab, (B, T)).astype("int32")
    trg = np.concatenate([np.zeros((B, 1), "int32"),
                          (src[:, :-1] + 1) % cfg.trg_vocab], axis=1)
    feed = {"src": jnp.asarray(src),
            "src_len": jnp.full(B, T, jnp.int32),
            "trg": jnp.asarray(trg),
            "trg_len": jnp.full(B, T, jnp.int32),
            "label": jnp.asarray((src + 1) % cfg.trg_vocab, jnp.int32)}
    key = jax.random.PRNGKey(0)

    step_fn = build_step_fn(main_p, [avg_cost.name], False, None)
    jfn, flops_ca = _aot_compile(jax.jit(step_fn, donate_argnums=(0,)),
                                 (persist, feed, key))
    flops_step = flops_ca or _transformer_analytic_flops(cfg, B, T)
    fetches, persist = jfn(persist, feed, key)
    # block_until_ready does not synchronize through the axon relay; a
    # device→host readback is the only reliable completion barrier.
    np.asarray(fetches[0])

    n = 50 if on_tpu else 5
    state = {"persist": persist, "loss": 0.0}

    def window():
        p = state["persist"]
        for _ in range(n):
            fetches, p = jfn(p, feed, key)
        state["persist"] = p
        state["loss"] = float(np.asarray(fetches[0]))

    dt = _median_window_time(window, 3 if on_tpu else 1)
    loss = state["loss"]
    assert np.isfinite(loss), f"non-finite loss {loss}"
    tokens_per_sec = n * B * T / dt

    peak = _peak_flops(jax.devices()[0])
    mfu = (flops_step * n / dt / peak) if peak else None
    evidence = {
        "mfu_method": "xla_cost_analysis" if flops_ca
                      else "analytic_matmul",
        "flops_per_step": flops_step,
        "wall_step_ms": round(dt / n * 1e3, 2),
    }
    if on_tpu and profile:
        # device-side per-step time from the profiler trace — wall
        # clock through the relay carries ±5-20% noise; the xplane
        # event durations are the corroborating record
        try:
            from paddle_tpu.profiler import profile_step_fn

            def one_step():
                fetches, state["persist"] = jfn(state["persist"], feed,
                                                key)
                return fetches

            dev_s, fams = profile_step_fn(one_step, steps=10)
            evidence["device_step_ms"] = round(dev_s * 1e3, 2)
            evidence["device_mfu"] = round(flops_step / dev_s / peak, 4)
            top = sorted(fams.items(), key=lambda kv: -kv[1])[:5]
            evidence["device_top_ops_ms"] = {
                k: round(v * 1e3, 2) for k, v in top}
        except Exception as e:
            evidence["device_profile_error"] = f"{type(e).__name__}: {e}"
    return tokens_per_sec, mfu, loss, evidence


def bench_resnet(platform):
    """ResNet-50 train-step images/sec (ref benchmark/fluid/models/resnet.py)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core.trace import build_step_fn
    from paddle_tpu.models import resnet

    on_tpu = platform in ("tpu", "axon")
    # B=128 measured +18% img/s over B=32 on v5e (better conv batching)
    B, HW = (128, 224) if on_tpu else (4, 64)
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            img = pt.layers.data("image", (3, HW, HW), dtype="float32")
            lbl = pt.layers.data("label", (1,), dtype="int64")
            predict = resnet.resnet(img, class_dim=1000, depth=50)
            loss = pt.layers.mean(pt.layers.cross_entropy(
                input=predict, label=lbl))
            pt.optimizer.Momentum(0.1, 0.9).minimize(loss)
    pt.amp.cast_program_to_bf16(main_p)

    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        pt.amp.cast_params_to_bf16(main_p, scope)
        persist = {v.name: scope.get(v.name)
                   for v in main_p.persistable_vars()}

    rng = np.random.RandomState(0)
    feed = {"image": jnp.asarray(rng.rand(B, 3, HW, HW).astype("float32")),
            "label": jnp.asarray(rng.randint(0, 1000, (B, 1)), jnp.int32)}
    key = jax.random.PRNGKey(0)
    step_fn = build_step_fn(main_p, [loss.name], False, None)
    jfn = jax.jit(step_fn, donate_argnums=(0,))
    fetches, persist = jfn(persist, feed, key)
    np.asarray(fetches[0])
    n = 20 if on_tpu else 2
    state = {"persist": persist, "loss": 0.0}

    def window():
        p = state["persist"]
        for _ in range(n):
            fetches, p = jfn(p, feed, key)
        state["persist"] = p
        state["loss"] = float(np.asarray(fetches[0]))

    dt = _median_window_time(window, 3 if on_tpu else 1)
    assert np.isfinite(state["loss"])
    return n * B / dt


def bench_flash_long_context(platform):
    """Long-context flash attention: causal fwd+bwd at T=32k (the
    unfused path cannot compile here — SURVEY §5 long-context story)."""
    if platform not in ("tpu", "axon"):
        return None
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa
    B, H, T, D = 1, 8, 32768, 64
    rng = np.random.RandomState(0)
    q, k, v = [jnp.asarray(rng.randn(B, H, T, D).astype("float32"),
                           jnp.bfloat16) for _ in range(3)]

    def loss_fn(q, k, v):
        out = fa.flash_attention(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)))
    out = g(q, k, v)
    np.asarray(out[0][0, 0, 0])
    n = 5

    def window():
        out = g(q, k, v)
        for _ in range(n - 1):
            out = g(q, k, v)
        np.asarray(out[0][0, 0, 0])

    dt = _median_window_time(window, 3) / n
    # causal fwd+bwd matmul flops: 3 passes * 2MNK * BHT^2D / 2
    fl = 12 * B * H * T * T * D * 0.5
    peak = _peak_flops(jax.devices()[0])
    return {"flash_attn_32k_causal_ms": round(dt * 1e3, 1),
            "flash_attn_32k_mfu": round(fl / dt / peak, 4)}


def bench_inference(platform):
    """InferenceEngine latency/throughput (ref inference/api/api_impl.cc
    deploy story): transformer encoder forward and ResNet-50 forward,
    jit-cached path plus the AOT-compiled (save_compiled/load_compiled)
    path for ResNet."""
    import tempfile
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.models import resnet

    on_tpu = platform in ("tpu", "axon")
    out = {}
    rng = np.random.RandomState(0)

    # --- ResNet-50 forward, B=32 ---
    B, HW = (32, 224) if on_tpu else (2, 64)
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            img = pt.layers.data("image", (3, HW, HW), dtype="float32")
            predict = resnet.resnet(img, class_dim=1000, depth=50)
    infer_p = main_p.clone(for_test=True)
    scope = pt.Scope()
    exe = pt.Executor()
    with pt.scope_guard(scope):
        exe.run(startup)
    eng = InferenceEngine(infer_p, ["image"], [predict], scope,
                          use_bf16=True)
    x = rng.rand(B, 3, HW, HW).astype("float32")
    eng.run({"image": x})  # compile
    n = 20 if on_tpu else 2
    dt = _median_window_time(
        lambda: [eng.run({"image": x}, return_numpy=False)
                 for _ in range(n)] and np.asarray(
            eng.run({"image": x})[0][0, :1]), 3) / (n + 1)
    out["resnet50_infer_images_per_sec"] = round(B / dt, 1)
    out["resnet50_infer_latency_ms"] = round(dt * 1e3, 2)

    # AOT roundtrip: save_compiled → load_compiled → run. TPU only:
    # exporting ResNet-50 StableHLO on CPU takes minutes and the CPU
    # number means nothing (the roundtrip itself is covered by tests)
    if not on_tpu:
        return out
    try:
        with tempfile.TemporaryDirectory() as d:
            eng.save_compiled(d, {"image": (B, 3, HW, HW)})
            pred = InferenceEngine.load_compiled(d)
            pred.run({"image": x})
            dt = _median_window_time(
                lambda: np.asarray(pred.run({"image": x})[0][0, :1]), 3)
            out["resnet50_infer_aot_latency_ms"] = round(dt * 1e3, 2)
    except Exception as e:
        out["resnet50_infer_aot_error"] = f"{type(e).__name__}: {e}"
    return out


def bench_deepfm(platform):
    """DeepFM CTR at scale (ref BASELINE config 5 + lookup_table_op.cc
    is_sparse): 8M-row embedding tables trained with lazy row-sparse
    Adam — update bandwidth O(batch), not O(vocab). Returns
    {examples/s, step ms, HBM peak} (VERDICT r3 #5)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core.trace import build_step_fn
    from paddle_tpu.models import deepfm

    on_tpu = platform in ("tpu", "axon")
    B, F = (4096, 26) if on_tpu else (64, 6)
    vocab = 8_000_000 if on_tpu else 1000
    # `bench.py --deepfm-vocab-rows=N` (env BENCH_DEEPFM_VOCAB_ROWS):
    # scale the CTR vocabulary; vocabularies past single-device HBM
    # belong to the sharded engine (`bench.py --sparse`, BENCH_sparse)
    env_vocab = os.environ.get("BENCH_DEEPFM_VOCAB_ROWS")
    if env_vocab:
        vocab = int(float(env_vocab))
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            feeds, loss, prob = deepfm.build_program(
                num_fields=F, vocab_size=vocab, embed_dim=16)
            pt.optimizer.Adam(1e-3).minimize(loss)
    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        persist = {v.name: scope.get(v.name)
                   for v in main_p.persistable_vars()}
    rng = np.random.RandomState(0)
    feed = {"feat_ids": jnp.asarray(
                rng.randint(0, vocab, (B, F, 1)), jnp.int32),
            "feat_vals": jnp.asarray(rng.rand(B, F).astype("float32")),
            "label": jnp.asarray(
                rng.randint(0, 2, (B, 1)).astype("float32"))}
    key = jax.random.PRNGKey(0)
    step_fn = build_step_fn(main_p, [loss.name], False, None)
    jfn = jax.jit(step_fn, donate_argnums=(0,))
    fetches, persist = jfn(persist, feed, key)
    np.asarray(fetches[0])
    n = 20 if on_tpu else 2
    state = {"persist": persist, "loss": 0.0}

    def window():
        p = state["persist"]
        for _ in range(n):
            fetches, p = jfn(p, feed, key)
        state["persist"] = p
        state["loss"] = float(np.asarray(fetches[0]))

    dt = _median_window_time(window, 3 if on_tpu else 1)
    assert np.isfinite(state["loss"])
    ids_np = np.asarray(feed["feat_ids"]).reshape(-1)
    out = {"deepfm_examples_per_sec": round(n * B / dt, 1),
           "deepfm_step_ms": round(dt / n * 1e3, 2),
           "deepfm_vocab_rows": vocab,
           # dedup opportunity of the batch (the sharded engine's wire
           # win scales with 1 - unique_ratio); this dense-path stage
           # exchanges nothing — the engine numbers live in
           # BENCH_sparse.json (`bench.py --sparse`)
           "deepfm_unique_ratio": round(
               len(np.unique(ids_np)) / ids_np.size, 4),
           "deepfm_exchange_bytes": 0}
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("peak_bytes_in_use"):
            out["deepfm_hbm_peak_gb"] = round(
                stats["peak_bytes_in_use"] / 2**30, 2)
    except Exception:
        pass
    return out


def bench_mnist(platform):
    """MNIST MLP train steps/sec (ref benchmark/fluid/mnist.py)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.core.trace import build_step_fn
    from paddle_tpu.models import mnist as mn

    B = 128
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        with pt.unique_name.guard():
            img = pt.layers.data("image", (784,), dtype="float32")
            lbl = pt.layers.data("label", (1,), dtype="int64")
            predict = mn.mlp(img)
            loss = pt.layers.mean(pt.layers.cross_entropy(
                input=predict, label=lbl))
            pt.optimizer.Adam(1e-3).minimize(loss)
    exe = pt.Executor()
    scope = pt.Scope()
    with pt.scope_guard(scope):
        exe.run(startup)
        persist = {v.name: scope.get(v.name)
                   for v in main_p.persistable_vars()}
    rng = np.random.RandomState(0)
    feed = {"image": jnp.asarray(rng.rand(B, 784).astype("float32")),
            "label": jnp.asarray(rng.randint(0, 10, (B, 1)), jnp.int32)}
    key = jax.random.PRNGKey(0)
    step_fn = build_step_fn(main_p, [loss.name], False, None)
    jfn = jax.jit(step_fn, donate_argnums=(0,))
    fetches, persist = jfn(persist, feed, key)
    np.asarray(fetches[0])
    n = 200
    state = {"persist": persist}

    def window():
        p = state["persist"]
        for _ in range(n):
            fetches, p = jfn(p, feed, key)
        state["persist"] = p
        np.asarray(fetches[0])

    dt = _median_window_time(window, 3)
    return n / dt


def _load_baseline():
    """Anchor for vs_baseline: prefer the driver-witnessed number over
    the builder-measured `published` one (VERDICT r3 #4)."""
    try:
        bp = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BASELINE.json")
        with open(bp) as f:
            b = json.load(f)
        for block in ("witnessed", "published"):
            v = b.get(block, {}).get("transformer_tokens_per_sec")
            if v:
                return float(v), block
    except Exception:
        pass
    return None, None


def run_benchmarks(platform, emit_progress=None):
    """Run every benchmark on the already-initialized backend; returns
    the result dict. When emit_progress is given, a snapshot of the
    accumulated result is emitted after EVERY completed sub-benchmark,
    so a kill at any moment leaves the best-so-far on stdout."""
    import jax
    result = {
        "metric": "transformer_base_train_tokens_per_sec",
        "value": 0.0,
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
    }

    only = os.environ.get("BENCH_ONLY", "").split(",")
    only = [s for s in only if s]
    want = lambda name: not only or name in only

    def progress():
        if emit_progress:
            emit_progress(dict(result, partial=True,
                               stage=_STAGE["stage"]))

    try:
        result["platform"] = platform
        result["device_kind"] = getattr(jax.devices()[0],
                                        "device_kind", "")
        progress()

        stage_s = result.setdefault("stage_seconds", {})

        def _stage_peak():
            """Per-stage HBM watermark: the memory ledger's
            read-and-reset peak, None when PADDLE_TPU_MEMLEDGER is off
            (the off path never imports the ledger)."""
            try:
                from paddle_tpu import telemetry as _tm
                if not _tm.memledger_enabled():
                    return None
                from paddle_tpu.telemetry import memledger as _ml
                return _ml.get().take_peak() or None
            except Exception:
                return None

        def _stamp_peak(stage):
            pk = _stage_peak()
            if pk:
                result.setdefault("peak_hbm_bytes", {})[stage] = pk

        _stage_peak()              # drop any pre-bench watermark
        _STAGE["stage"] = "transformer"
        if want("transformer"):
            _t0 = time.perf_counter()
            tokens_per_sec, mfu, loss, evidence = \
                bench_transformer(platform)
            stage_s["transformer"] = round(time.perf_counter() - _t0, 1)
            _stamp_peak("transformer")
            result["value"] = round(tokens_per_sec, 1)
            if mfu is not None:
                result["mfu"] = round(mfu, 4)
            result["loss"] = round(loss, 4)
            result["evidence"] = evidence

            baseline, block = _load_baseline()
            if baseline:
                ratio = tokens_per_sec / baseline
                # keep small CPU-fallback ratios visible (0.0002, not 0.0)
                result["vs_baseline"] = float(f"{ratio:.3g}")
                result["baseline_block"] = block
            else:
                result["vs_baseline"] = 1.0
            progress()

        # priority order under the fixed budget: the stages a verdict
        # still lacks a witnessed number for (inference AOT latency,
        # DeepFM-at-scale) run BEFORE the slower secondary axes, so a
        # budget kill costs the least-important tail, not them
        def run_stage(stage, names, fn, scalar_key=None, err_key=None):
            """`names`: accepted BENCH_ONLY selector tokens (first is
            the stage_seconds label); `err_key` preserves the error-key
            names earlier BENCH artifacts used."""
            _STAGE["stage"] = stage
            if only and not any(n in only for n in names):
                return
            t0 = time.perf_counter()
            try:
                out = fn(platform)
                if scalar_key:
                    result[scalar_key] = round(out, 1)
                elif out:
                    result.update(out)
            except Exception as e:
                result[err_key or f"{names[0]}_error"] = \
                    f"{type(e).__name__}: {e}"
            stage_s[names[0]] = round(time.perf_counter() - t0, 1)
            _stamp_peak(names[0])
            progress()

        run_stage("inference", ("inference",), bench_inference)
        run_stage("deepfm", ("deepfm",), bench_deepfm)
        run_stage("resnet50_images_per_sec", ("resnet", "resnet50"),
                  bench_resnet, scalar_key="resnet50_images_per_sec",
                  err_key="resnet50_images_per_sec_error")
        run_stage("mnist_mlp_steps_per_sec", ("mnist",), bench_mnist,
                  scalar_key="mnist_mlp_steps_per_sec",
                  err_key="mnist_mlp_steps_per_sec_error")
        def bench_transformer_b256(platform):
            """Large-batch operating point (B=256): amortizes the
            non-matmul tail, so MFU reads closer to the matmul
            ceiling. Secondary record — the headline keeps the SURVEY
            B=64 config for baseline comparability."""
            if platform not in ("tpu", "axon"):
                return {}
            tps, mfu, loss, ev = bench_transformer(platform, batch=256,
                                                   profile=False)
            return {"transformer_b256_tokens_per_sec": round(tps, 1),
                    "transformer_b256_mfu": round(mfu, 4) if mfu else None,
                    "transformer_b256_wall_step_ms":
                        ev.get("wall_step_ms")}

        run_stage("transformer_b256", ("b256", "transformer_b256"),
                  bench_transformer_b256)
        run_stage("flash_long_context", ("flash",),
                  bench_flash_long_context,
                  err_key="flash_long_context_error")
    except Exception as e:
        result["error"] = f"{type(e).__name__}: {e}"
        result["stage"] = _STAGE["stage"]
        result["traceback"] = traceback.format_exc()[-1500:]
    result.pop("partial", None)
    if "error" not in result:
        result.pop("stage", None)
    return result


def _enable_compile_cache():
    """Persistent XLA compilation cache shared across bench runs: the
    stage budget is dominated by first-compile time through the relay
    (~20-40s per executable), and the driver's run typically follows a
    builder run of the identical configs on the same machine — a warm
    cache turns most of that into milliseconds. Best-effort: backends
    that can't serialize executables just ignore the cache."""
    import jax
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_compile_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    except Exception:
        pass


_HISTORY_SCHEMA = "paddle_tpu.bench.history.v1"

# result key -> (unit, stage) for the perf-history spine: one compact
# record per completed bench stage lands in BENCH_history.jsonl, the
# rolling trajectory `tpustat --slo` regression-gates against
_HISTORY_METRICS = (
    ("value", "tokens/sec", "transformer"),
    ("mfu", "mfu", "transformer"),
    ("resnet50_infer_images_per_sec", "images/sec", "inference"),
    ("resnet50_infer_latency_ms", "ms", "inference"),
    ("deepfm_examples_per_sec", "examples/sec", "deepfm"),
    ("deepfm_step_ms", "ms", "deepfm"),
    ("resnet50_images_per_sec", "images/sec", "resnet"),
    ("mnist_mlp_steps_per_sec", "steps/sec", "mnist"),
    ("transformer_b256_tokens_per_sec", "tokens/sec", "b256"),
    ("transformer_b256_mfu", "mfu", "b256"),
    ("flash_attn_32k_causal_ms", "ms", "flash"),
    ("kern_decode_fp32_off_ms", "ms", "kern"),
    ("kern_decode_fp32_on_ms", "ms", "kern"),
    ("kern_decode_int8_off_ms", "ms", "kern"),
    ("kern_decode_int8_on_ms", "ms", "kern"),
)


def _git_sha():
    try:
        import subprocess
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        sha = out.stdout.strip()
        return sha or None
    except Exception:
        return None


def _host_fingerprint():
    """Stable 12-hex id for the machine class a record was measured
    on. Same-fingerprint records are directly comparable; across
    fingerprints only the calibration ratio makes them commensurable."""
    import hashlib
    import platform as _pf
    probe = "|".join((_pf.system(), _pf.machine(),
                      _pf.processor() or "",
                      str(os.cpu_count() or 0)))
    return hashlib.sha1(probe.encode()).hexdigest()[:12]


_CALIB_MS = None


def _calibrate():
    """Fixed host-CPU calibration microbenchmark: best-of-5 wall time
    for 64 seeded 128x128 fp32 matmuls (~270 MFLOP per trial). The
    SAME work every run, every box, forever — so the ratio of two
    records' `calib_ms` is the relative speed of the boxes that
    produced them, and the history gate can normalize a spine that
    spans machines instead of flagging a slower box as a perf
    regression. Cached per process (one stamp per bench run)."""
    global _CALIB_MS
    if _CALIB_MS is None:
        import numpy as np
        rng = np.random.RandomState(0)
        a = rng.randn(128, 128).astype(np.float32)
        b = rng.randn(128, 128).astype(np.float32)
        for _ in range(8):
            (a @ b)                      # warm the BLAS path
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(64):
                (a @ b)
            best = min(best, time.perf_counter() - t0)
        _CALIB_MS = round(best * 1e3, 4)
    return _CALIB_MS


def _history_records(result, now=None):
    """The schema'd per-stage records for one bench result. The
    headline 'value' is renamed to its real metric name; zero values
    from never-ran stages are skipped (a bootstrap artifact must not
    drag the rolling median to 0)."""
    now = now if now is not None else time.time()
    sha = _git_sha()
    common = {"schema": _HISTORY_SCHEMA,
              "platform": result.get("platform"),
              "device_kind": result.get("device_kind"),
              "git_sha": sha, "unix_time": round(now, 1),
              # calibration spine: the fixed microbenchmark's wall
              # time plus the host class it ran on. history_gate
              # divides these out, so records from differently-sized
              # CI boxes gate against each other fairly
              "calib_ms": _calibrate(),
              "fingerprint": _host_fingerprint()}
    records = []
    for key, unit, stage in _HISTORY_METRICS:
        v = result.get(key)
        if not isinstance(v, (int, float)) or not v:
            continue
        if key == "value":
            # the headline metric describes itself; the table's
            # unit/stage are only the default (transformer) labels
            metric = result.get("metric", key)
            unit = result.get("unit", unit)
            stage = result.get("history_stage", stage)
        else:
            metric = key
        records.append(dict(common, metric=metric, value=v,
                            unit=unit, stage=stage))
    # per-stage HBM watermarks (memory-ledger runs only — the dict is
    # absent with PADDLE_TPU_MEMLEDGER off, so the spine is unchanged)
    for stage, pk in sorted((result.get("peak_hbm_bytes")
                             or {}).items()):
        if isinstance(pk, (int, float)) and pk:
            records.append(dict(common,
                                metric=f"{stage}_peak_hbm_bytes",
                                value=int(pk), unit="bytes",
                                stage=stage))
    return records


def _append_history(result, path=None):
    """Append this run's per-stage records to the history spine
    (BENCH_HISTORY_PATH overrides the default repo-root
    BENCH_history.jsonl). Best-effort: any failure returns None and
    never disturbs the bench artifacts or stdout contract."""
    try:
        records = _history_records(result)
        if not records:
            return None
        path = path or os.environ.get("BENCH_HISTORY_PATH") \
            or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_history.jsonl")
        with open(path, "a") as f:
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        return path
    except Exception:
        return None


def _write_telemetry_artifact(path=None):
    """BENCH_telemetry.json alongside BENCH_probe.json: the full metric
    snapshot (+ span count) of the bench run when telemetry is on.
    Telemetry off (the default): returns None, writes NOTHING, and
    touches no stdout — the bench-contract final-line pins stay intact
    (tests/test_bench_contract.py)."""
    try:
        from paddle_tpu import telemetry
    except Exception:
        return None
    if not telemetry.enabled():
        return None
    snap = telemetry.snapshot()
    path = path or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "BENCH_telemetry.json")
    try:
        with open(path, "w") as f:
            json.dump({"schema": "paddle_tpu.bench.telemetry.v1",
                       "metrics": snap,
                       "spans": len(telemetry.iter_spans())},
                      f, indent=1, default=str)
    except OSError:
        return None
    return path


def _child_main():
    """BENCH_CHILD=1 mode: assume the default backend (TPU, or CPU when
    the parent forced JAX_PLATFORMS=cpu), stream a progress line after
    each sub-benchmark, print the final line last. Any hang here is the
    parent's problem — it holds the kill timer."""
    import jax
    _enable_compile_cache()
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the TPU-relay plugin hijacks get_backend and initializes its
        # relay connection even under JAX_PLATFORMS=cpu — with the
        # relay down the "CPU" child then hangs in jax.devices(); the
        # config knob actually stops it
        jax.config.update("jax_platforms", "cpu")
    platform = jax.devices()[0].platform  # may hang; parent supervises
    result = run_benchmarks(platform, emit_progress=_emit)
    # artifact writes happen BEFORE the final emit: the last stdout
    # line must stay the result line no matter what the writes do
    _append_history(result)
    _write_telemetry_artifact()
    _emit(result)


class _Supervisor:
    """Parent mode: never touches a backend in-process; guarantees the
    last stdout line is always the best complete JSON result so far."""

    def __init__(self):
        self.best = {
            "metric": "transformer_base_train_tokens_per_sec",
            "value": 0.0, "unit": "tokens/sec", "vs_baseline": 0.0,
            "platform": "none", "stage": "bootstrap",
            "error": "bootstrap: no benchmark has completed yet",
        }
        self.t0 = time.monotonic()

    def consider(self, obj):
        """Re-emit a child line iff it is at least as good as the best
        seen — a later equal-score line carries MORE sub-benchmarks."""
        if _score(obj) >= _score(self.best):
            self.best = obj
            _emit(obj)

    def _flush_and_die(self, signum, frame):
        # guarantee the last stdout line is complete JSON even if a
        # child write raced the kill: leading newline terminates any
        # half-written line (a signal can interrupt a non-_emit write),
        # and _emit's lock serializes against the pump threads
        self.best["signal"] = signum
        _emit(_compact_final(self.best), lead="\n")
        os._exit(0)

    def _stream_child(self, env, timeout):
        """Run a benchmark child, re-emitting every improved JSON line
        the moment it arrives. Returns (rc, stderr_tail)."""
        import subprocess
        import threading
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
        err_tail = [""]

        def pump_out():
            for line in p.stdout:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except Exception:
                    continue
                self.consider(obj)

        def pump_err():
            for line in p.stderr:
                err_tail[0] = (err_tail[0] + line)[-800:]

        threads = [threading.Thread(target=pump_out, daemon=True),
                   threading.Thread(target=pump_err, daemon=True)]
        for t in threads:
            t.start()
        try:
            p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            # SIGTERM first: give the PJRT client a chance to close its
            # relay session — a SIGKILLed child can leave the
            # single-client relay lease wedged for every later probe
            p.terminate()
            try:
                p.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        for t in threads:
            t.join(timeout=5.0)
        return p.returncode, err_tail[0]

    def run(self):
        import signal
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, self._flush_and_die)
        _emit(self.best)  # t=0: the artifact can never be empty again

        budget = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "480"))
        child_timeout = float(os.environ.get("BENCH_CHILD_TIMEOUT_S",
                                             "330"))
        remaining = lambda: budget - (time.monotonic() - self.t0)
        attempts, children = [], []
        cpu_done = False

        def probe(mode, cap, platforms=None):
            rec = _probe_tpu(
                timeout=max(min(cap, remaining() - 40.0), 8.0),
                mode=mode, platforms=platforms)
            attempts.append(rec)
            return rec

        def probe_hit(rec):
            return rec["outcome"] == "ok" \
                and rec.get("platform") in ("tpu", "axon")

        def tpu_child(platforms):
            env = dict(os.environ, BENCH_CHILD="1")
            if platforms:
                env["JAX_PLATFORMS"] = platforms
            rc, err = self._stream_child(
                env, timeout=max(min(child_timeout,
                                     remaining() - 20.0), 5.0))
            children.append(
                {"kind": "tpu", "platforms": platforms or "(default)",
                 "rc": rc, "stderr_tail": _text_tail(err)})
            return _score(self.best) >= 2

        # Staged probe plan (round-4 verdict: record EVERY attempt,
        # escalate, and leave a trail that localizes the failure):
        done, no_tpu = False, False
        forced = os.environ.get("JAX_PLATFORMS", "")
        if forced and "tpu" not in forced and "axon" not in forced:
            # the operator pinned a non-TPU backend (CPU CI): don't
            # burn budget probing a chip we were told not to use
            no_tpu = True
        # A: quick default-env compute probe — the happy path leaves
        # ~330 s for the TPU child.
        if not no_tpu and remaining() > 110.0:
            rec = probe("compute", 70.0)
            if probe_hit(rec):
                done = tpu_child(None)
            elif rec["outcome"] == "ok":
                # default env resolved to CPU — is a TPU plugin present
                # at all? Explicit JAX_PLATFORMS=axon answers fast
                # (error = plugin absent → stop probing for good).
                rec2 = probe("compute", 60.0, platforms="axon")
                if probe_hit(rec2):
                    done = tpu_child("axon")
                elif rec2["outcome"] == "error":
                    no_tpu = True
            elif rec["outcome"] == "timeout":
                # diagnosis only: a listing probe separates "jax import
                # / plugin load hangs" from "device init hangs" from
                # "listing works but dispatch hangs" via stage markers,
                # and the C-level probe (native predictor + real axon
                # session options) localizes a hang to the relay itself
                # when PJRT_Client_Create stalls below jax too
                probe("list", 40.0)
                probe("cprobe", 45.0)
        # B: guarantee a result line regardless — CPU fallback child.
        if not done and remaining() > 40.0:
            cpu_done = True
            rc, err = self._stream_child(
                dict(os.environ, BENCH_CHILD="1", JAX_PLATFORMS="cpu"),
                timeout=max(min(240.0, remaining() - 15.0), 5.0))
            children.append({"kind": "cpu", "rc": rc,
                             "stderr_tail": _text_tail(err)})
        # C: escalated re-probe, explicit platform selection.
        if not done and not no_tpu and remaining() > 70.0:
            rec = probe("compute", 110.0, platforms="axon")
            if probe_hit(rec):
                done = tpu_child("axon")
            elif rec["outcome"] == "error":
                # explicit plugin selection failed outright (plugin
                # absent or broken) — a default-env retry can't win
                no_tpu = True
        # D: final default-env probe with whatever budget is left.
        if not done and not no_tpu and remaining() > 70.0:
            rec = probe("compute", remaining() - 50.0)
            if probe_hit(rec):
                done = tpu_child(None)
        # Make the last line the best-known result with a COMPACT probe
        # summary; the complete attempt/child forensic trail goes to the
        # BENCH_probe.json artifact (the round-5 embedded trail overflowed
        # the driver's tail capture and killed the whole artifact).
        probe_summary = {
            "witnessed_tpu": bool(done), "no_tpu_plugin": no_tpu,
            "cpu_fallback_ran": cpu_done,
            "tpu_children": sum(1 for c in children
                                if c["kind"] == "tpu"),
            "attempts": len(attempts), "children": len(children),
            "seconds": round(time.monotonic() - self.t0, 1),
            "trail": "BENCH_probe.json"}
        trail_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_probe.json")
        try:
            with open(trail_path, "w") as f:
                json.dump({"probe": dict(probe_summary,
                                         attempts=attempts,
                                         children=children),
                           "best": self.best}, f, indent=1,
                          default=str)
        except OSError:
            probe_summary["trail"] = "(unwritable)"
        self.best["probe"] = probe_summary
        _emit(_compact_final(self.best))


def _grad_sync_mode(steps=10, n_devices=8, mode="int8"):
    """`bench.py --grad-sync=MODE`: A/B the gradient-sync policy layer
    (parallel/gradsync.py) against fp32 sync on the data-parallel stage
    — the round-4 `--flash-bf16-softmax` pattern for ROADMAP item 2.
    Runs the MNIST-MLP DP stage over an 8-virtual-device CPU mesh (the
    policy layer is wire-format logic; trace-time byte accounting is
    identical on any backend), measures `collective.all_reduce.bytes`,
    the gradsync raw/wire counters, steps/sec, and final loss per
    policy, and prints ONE JSON line + the BENCH_gradsync.json
    artifact. The acceptance bar: int8 cuts all-reduce bytes >= 3.5x
    vs fp32."""
    import __graft_entry__ as graft
    restore = graft._force_cpu_mesh(n_devices)
    try:
        import jax
        import paddle_tpu as pt
        from paddle_tpu import layers, telemetry

        def build():
            img = layers.data("img", shape=[64])
            label = layers.data("label", shape=[1], dtype="int64")
            h = layers.fc(img, size=256, act="relu")
            h = layers.fc(h, size=128, act="relu")
            pred = layers.fc(h, size=10, act="softmax")
            loss = layers.mean(layers.cross_entropy(pred, label))
            pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
            return loss

        rng = np.random.RandomState(0)
        feed = {"img": rng.randn(64, 64).astype("float32"),
                "label": rng.randint(0, 10, (64, 1)).astype("int64")}
        policies = ["fp32"] + ([mode] if mode != "fp32" else [])
        per_policy = {}
        was_on = telemetry.enabled()
        for pol in policies:
            main_p, startup_p = pt.Program(), pt.Program()
            with pt.program_guard(main_p, startup_p):
                with pt.unique_name.guard():
                    loss = build()
            main_p.random_seed = startup_p.random_seed = 7
            scope = pt.Scope()
            telemetry.enable()
            telemetry.reset()
            try:
                with pt.scope_guard(scope):
                    exe = pt.Executor(pt.CPUPlace())
                    exe.run(startup_p)
                    pexe = pt.ParallelExecutor(
                        loss_name=loss.name, main_program=main_p,
                        scope=scope, grad_sync=pol)
                    last = float(np.asarray(pexe.run(
                        feed=feed, fetch_list=[loss])[0]))  # compile
                    t0 = time.perf_counter()
                    for _ in range(steps):
                        last = float(np.asarray(pexe.run(
                            feed=feed, fetch_list=[loss])[0]))
                    dt = time.perf_counter() - t0
                snap = telemetry.snapshot()
            finally:
                telemetry.reset()
                if not was_on:
                    telemetry.disable()
            per_policy[pol] = {
                "all_reduce_bytes": snap.get(
                    "collective.all_reduce.bytes", 0),
                "all_reduce_count": snap.get(
                    "collective.all_reduce.count", 0),
                "gradsync_raw_bytes": snap.get("gradsync.raw_bytes", 0),
                "gradsync_wire_bytes": snap.get("gradsync.wire_bytes",
                                                0),
                "gradsync_buckets": snap.get("gradsync.buckets", 0),
                "steps_per_sec": round(steps / dt, 1),
                "final_loss": round(last, 5),
            }
        a, b = per_policy["fp32"], per_policy[policies[-1]]
        ratio = (a["all_reduce_bytes"] / b["all_reduce_bytes"]
                 if b["all_reduce_bytes"] else None)
        result = {
            "metric": "grad_sync_all_reduce_bytes_ratio",
            "value": round(ratio, 3) if ratio else 0.0,
            "unit": "x (fp32 bytes / policy bytes)",
            "vs_baseline": round(ratio, 3) if ratio else 0.0,
            "platform": "cpu",
            "grad_sync_mode": mode,
            "n_devices": n_devices,
            "steps": steps,
            "per_policy": per_policy,
            "loss_abs_delta": round(
                abs(a["final_loss"] - b["final_loss"]), 5),
            "pass_3p5x": bool(ratio and ratio >= 3.5),
        }
        try:
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_gradsync.json")
            with open(path, "w") as f:
                json.dump({"schema": "paddle_tpu.bench.gradsync.v1",
                           **result}, f, indent=1)
        except OSError:
            pass
        _emit(result)
        return 0 if mode == "fp32" or result["pass_3p5x"] else 1
    finally:
        restore()


def _sparse_mode(vocab_rows=100_000_000, steps=8, n_devices=8):
    """`bench.py --sparse[=VOCAB_ROWS]`: DeepFM through the sharded
    embedding engine (parallel/sparse.py, ROADMAP item 5) on an
    8-virtual-device CPU mesh. The tables are never materialized on
    one device: startup init is stripped and each mesh member seeds
    only its vocab/N rows (engine.init_shards), so vocab_rows=1e8
    (the default — the pserver-era scale) holds ~400 MB of table per
    member instead of 3.2 GB anywhere. Ids follow a hot-set mixture
    (30% of positions from 1k hot ids — CTR-style popularity skew) so
    the unique-ids dedup has a measurable ratio. SGD keeps the 1e8
    footprint at 1x table (lazy-Adam moments would 3x it; the engine
    supports both). Prints ONE JSON line + BENCH_sparse.json with
    examples/s, the dedup ratio, and the per-step exchange bytes."""
    import __graft_entry__ as graft
    restore = graft._force_cpu_mesh(n_devices)
    try:
        import jax
        import paddle_tpu as pt
        from paddle_tpu import telemetry
        from paddle_tpu.models import deepfm
        from paddle_tpu.parallel import sparse as tpusparse

        B, F, D = 512, 26, 8
        main_p, startup = pt.Program(), pt.Program()
        with pt.program_guard(main_p, startup):
            with pt.unique_name.guard():
                feeds, loss, prob = deepfm.build_program(
                    num_fields=F, vocab_size=vocab_rows, embed_dim=D,
                    is_distributed=True)
                pt.optimizer.SGD(0.1).minimize(loss)
        main_p.random_seed = startup.random_seed = 1
        tables = tpusparse.discover_tables(main_p)
        tpusparse.strip_table_init(startup, tables)
        rng = np.random.RandomState(0)
        hot = rng.randint(0, vocab_rows, 1000)
        flat = np.where(rng.rand(B * F) < 0.3,
                        hot[rng.randint(0, 1000, B * F)],
                        rng.randint(0, vocab_rows, B * F))
        feed = {"feat_ids": flat.reshape(B, F, 1).astype("int64"),
                "feat_vals": rng.rand(B, F).astype("float32"),
                "label": rng.randint(0, 2, (B, 1)).astype("float32")}
        was_on = telemetry.enabled()
        telemetry.enable()
        telemetry.reset()
        scope = pt.Scope()
        try:
            with pt.scope_guard(scope):
                exe = pt.Executor(pt.CPUPlace())
                exe.run(startup)
                pexe = pt.ParallelExecutor(
                    loss_name=loss.name, main_program=main_p,
                    scope=scope, sparse="shard")
                t0 = time.perf_counter()
                pexe.sparse_engine.init_shards(scope, seed=1)
                init_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                l0 = float(np.asarray(pexe.run(
                    feed=feed, fetch_list=[loss])[0]))  # compile
                compile_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                for _ in range(steps):
                    last = float(np.asarray(pexe.run(
                        feed=feed, fetch_list=[loss])[0]))
                dt = time.perf_counter() - t0
                eng = pexe.sparse_engine
                shard_rows = {
                    t: eng.tables[t].local_rows for t in tables}
                stats = {t: np.asarray(
                    scope.get(tpusparse.STATS_PREFIX + t))
                    for t in tables}
            snap = telemetry.snapshot()
        finally:
            telemetry.reset()
            if not was_on:
                telemetry.disable()
        uniq = {t: round(float(s[1] / max(s[0], 1)), 4)
                for t, s in stats.items()}
        exchange = {t: int(snap.get(f"embed.{t}.exchange_bytes", 0))
                    for t in tables}
        ratio = sum(uniq.values()) / max(len(uniq), 1)
        result = {
            "metric": "sparse_deepfm_examples_per_sec",
            "value": round(steps * B / dt, 1),
            "unit": "examples/sec",
            "vs_baseline": 0.0,
            "platform": "cpu",
            "vocab_rows": vocab_rows,
            "n_devices": n_devices,
            "embed_dim": D,
            "batch": B,
            "fields": F,
            "step_ms": round(dt / steps * 1e3, 2),
            "init_shards_s": round(init_s, 1),
            "compile_s": round(compile_s, 1),
            "unique_ratio": uniq,
            "unique_ratio_mean": round(ratio, 4),
            # trace-time wire accounting: one traced step's all-to-all
            # payload per table (ids out + rows back, both directions)
            "exchange_bytes_per_step": exchange,
            "rows_per_shard": shard_rows,
            "loss_first": round(l0, 5),
            "loss_last": round(last, 5),
            "trains": bool(np.isfinite(last) and last < l0),
        }
        try:
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_sparse.json")
            with open(path, "w") as f:
                json.dump({"schema": "paddle_tpu.bench.sparse.v1",
                           **result}, f, indent=1)
        except OSError:
            pass
        _emit(result)
        return 0 if result["trains"] else 1
    finally:
        restore()


def _async_mode(k=4, steps=40):
    """`bench.py --async-steps=K`: A/B the asynchronous step pipeline
    (tpupipe, core/pipeline_exec.py) against the synchronous executor
    hot path — the round-4 `--flash-bf16-softmax` pattern. Two stages:

    - mlp_feedbound: a feed-transfer-bound MLP (32 MB of feed per step
      against a small matmul), the workload double_buffer existed for.
      The SYNC leg is the PR-9 path exactly (per-step feed re-put,
      donating, k=0); the PIPELINED leg is this PR's full feature set
      (identity feed cache + async_steps=K + donate_state=False so
      dispatch stays async on this jax's CPU backend). Acceptance:
      >= 20% step-time reduction with bit-identical per-step losses.
    - transformer: the flagship model under the same A/B (reported,
      no bar — its step is compute-bound, the honest null case).

    Caveat recorded in the artifact: this CI image has ONE host core,
    so the window cannot overlap host work with device compute here —
    the measured win is feed-put elimination + deferred readback; on
    multi-core hosts / real TPUs the same knob adds compute overlap
    (donation + async dispatch coexist on TPU backends).
    Prints ONE JSON line + the BENCH_pipeline.json artifact."""
    import __graft_entry__ as graft
    restore = graft._force_cpu_mesh(1)
    try:
        import jax
        # jax-0.4.37's CPU backend dispatches synchronously by
        # default; the pipeline needs real async dispatch to measure
        # anything (TPU backends are always async)
        jax.config.update("jax_cpu_enable_async_dispatch", True)
        import paddle_tpu as pt
        from paddle_tpu import layers, telemetry

        def hist_sum(snap, name):
            v = snap.get(name)
            return float(v.get("sum", 0.0)) if isinstance(v, dict) \
                else 0.0

        def run_leg(build_fn, feed, n, *, async_k, cache,
                    donate, seed=3):
            main_p, startup_p = pt.Program(), pt.Program()
            with pt.program_guard(main_p, startup_p):
                with pt.unique_name.guard():
                    fetch_var = build_fn()
            main_p.random_seed = startup_p.random_seed = seed
            scope = pt.Scope()
            was_on = telemetry.enabled()
            telemetry.enable()
            telemetry.reset()
            try:
                with pt.scope_guard(scope):
                    exe = pt.Executor(pt.CPUPlace())
                    exe.feed_cache = cache
                    exe.donate_state = donate
                    exe.run(startup_p)
                    exe.run(main_p, feed=feed,
                            fetch_list=[fetch_var])      # compile
                    telemetry.reset()
                    t0 = time.perf_counter()
                    outs = [exe.run(main_p, feed=feed,
                                    fetch_list=[fetch_var],
                                    async_steps=async_k or None)
                            for _ in range(n)]
                    if async_k:
                        exe.drain()
                    wall = time.perf_counter() - t0
                    losses = [np.asarray(o[0]).tobytes() for o in outs]
                    final = float(np.frombuffer(losses[-1],
                                                np.float32)[0])
                snap = telemetry.snapshot()
            finally:
                telemetry.reset()
                if not was_on:
                    telemetry.disable()
            stall_s = hist_sum(snap, "executor.pending_wait_seconds") \
                + hist_sum(snap, "executor.fetch_readback_seconds")
            return {
                "step_ms": round(wall / n * 1e3, 2),
                "wall_s": round(wall, 3),
                "final_loss": final,
                "feed_put_reused": int(
                    snap.get("executor.feed_put.reused", 0)),
                # host time spent BLOCKED on device results; the
                # overlap fraction below is 1 - stall/wall
                "stall_s": round(stall_s, 4),
                "_losses": losses,
            }

        rng = np.random.RandomState(0)
        stages = {}

        # ---- stage 1: feed-bound MLP (the acceptance stage) ----
        B, D, H = 4096, 2048, 32
        xs = rng.rand(B, D).astype("float32")
        ys = rng.rand(B, 1).astype("float32")
        # frozen batch: the identity cache only reuses buffers that
        # CANNOT be mutated (or feed_cache="trust") — mark them
        # read-only, the documented fixed-batch idiom
        xs.flags.writeable = False
        ys.flags.writeable = False

        def build_mlp():
            x = layers.data("x", shape=[D])
            y = layers.data("y", shape=[1])
            h = layers.fc(x, size=H, act="relu")
            pred = layers.fc(h, size=1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            pt.optimizer.SGD(0.1).minimize(loss)
            return loss

        feed = {"x": xs, "y": ys}
        sync = run_leg(build_mlp, feed, steps,
                       async_k=0, cache=False, donate=True)
        pipe = run_leg(build_mlp, feed, steps,
                       async_k=k, cache=True, donate=False)
        ident = sync.pop("_losses") == pipe.pop("_losses")
        red = 100.0 * (1.0 - pipe["step_ms"] / sync["step_ms"])
        stages["mlp_feedbound"] = {
            "batch": B, "dim": D, "hidden": H, "steps": steps,
            "feed_mb": round((xs.nbytes + ys.nbytes) / 2**20, 1),
            "sync": sync, "pipelined": pipe,
            "step_time_reduction_pct": round(red, 1),
            "overlap_fraction": round(
                1.0 - pipe["stall_s"] / max(pipe["wall_s"], 1e-9), 4),
            "bit_identical_losses": ident,
        }

        # ---- stage 2: transformer (reported; compute-bound) ----
        from paddle_tpu.models import transformer as tfm

        def build_tfm():
            cfg = tfm.TransformerConfig(
                src_vocab=512, trg_vocab=512, max_len=32,
                d_model=128, d_inner=256, n_head=4, n_layer=2,
                dropout=0.0)
            feeds, avg_cost, tok = tfm.build_program(cfg, maxlen=32)
            pt.optimizer.Adam(1e-3).minimize(avg_cost)
            return avg_cost

        tb, tt = 8, 32
        src = rng.randint(3, 512, (tb, tt)).astype("int32")
        trg = np.concatenate([np.zeros((tb, 1), "int32"),
                              (src[:, :-1] + 1) % 512], axis=1)
        tfm_feed = {"src": src,
                    "src_len": np.full(tb, tt, "int32"),
                    "trg": trg,
                    "trg_len": np.full(tb, tt, "int32"),
                    "label": ((src + 1) % 512).astype("int32")}
        for arr in tfm_feed.values():
            arr.flags.writeable = False
        t_steps = 10
        sync_t = run_leg(build_tfm, tfm_feed, t_steps,
                         async_k=0, cache=False, donate=True)
        pipe_t = run_leg(build_tfm, tfm_feed, t_steps,
                         async_k=k, cache=True, donate=False)
        ident_t = sync_t.pop("_losses") == pipe_t.pop("_losses")
        stages["transformer"] = {
            "batch": tb, "seq": tt, "steps": t_steps,
            "sync": sync_t, "pipelined": pipe_t,
            "step_time_reduction_pct": round(
                100.0 * (1.0 - pipe_t["step_ms"] / sync_t["step_ms"]),
                1),
            "bit_identical_losses": ident_t,
        }

        ok = bool(red >= 20.0
                  and stages["mlp_feedbound"]["bit_identical_losses"])
        result = {
            "metric": "pipeline_step_time_reduction_pct",
            "value": round(red, 1),
            "unit": "% (feed-bound stage, sync vs pipelined)",
            "vs_baseline": round(red, 1),
            "platform": "cpu",
            "async_steps": k,
            "host_cpus": os.cpu_count(),
            "legs": {
                "sync": "PR-9 path: per-step feed re-put, donating, "
                        "k=0",
                "pipelined": "feed identity cache + async window "
                             f"k={k} + donate_state=False (CPU async "
                             "dispatch)"},
            "single_core_note": (
                "1 host core on this image: the window cannot overlap "
                "host work with device compute here, so the measured "
                "win is feed-put elimination + deferred readback; "
                "multi-core hosts / TPUs add compute overlap on top"
            ) if (os.cpu_count() or 1) <= 1 else None,
            "stages": stages,
            "pass_20pct": ok,
        }
        try:
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "BENCH_pipeline.json")
            with open(path, "w") as f:
                json.dump({"schema": "paddle_tpu.bench.pipeline.v1",
                           **result}, f, indent=1)
        except OSError:
            pass
        _emit(result)
        return 0 if ok else 1
    finally:
        restore()


def _kern_mode(steps=24, maxlen=16, slots=4):
    """`bench.py --kern`: A/B the ops/kern registry dispatch seam on
    the incremental-decode stage — PADDLE_TPU_KERN=off (the
    byte-identical jnp lowering) vs registry ON with the Pallas
    interpreter forced. CPU-honesty note recorded in the artifact:
    interpret-mode Pallas is SLOWER than fused XLA on CPU, so the
    wall-time columns here are evidence the kernels actually ran and
    match token-for-token, not a speed claim — the speed claim needs
    the chip, where the same seam dispatches compiled kernels. Two
    variants: fp32 KV cache (decode_attend) and int8 block-quantized
    KV cache (int8_quant at the cache writes + fused
    dequant_attend_int8). Prints ONE JSON line + BENCH_kernels.json
    and appends paddle_tpu.bench.history.v1 records."""
    import paddle_tpu as pt
    from paddle_tpu.core import framework as fw
    from paddle_tpu.models import transformer as tfm

    # seeded tiny stack (the test_serving_farm recipe): wide random
    # params so greedy decode produces varied, comparable tokens
    cfg = tfm.TransformerConfig(src_vocab=64, trg_vocab=64,
                                max_len=maxlen, d_model=32, d_inner=64,
                                n_head=4, n_layer=2, dropout=0.0,
                                label_smooth_eps=0.0)
    infer, start = fw.Program(), fw.Program()
    with pt.program_guard(infer, start):
        with pt.unique_name.guard():
            tfm.build_infer_program(cfg, maxlen=maxlen)
    exe = pt.Executor(pt.CPUPlace())
    exe.run(start)
    rng = np.random.RandomState(7)
    scope = pt.global_scope()
    params = {}
    for v in infer.persistable_vars():
        a = np.asarray(scope.get(v.name))
        if v.name.startswith("layer_norm") and v.name.endswith(".w_0"):
            nv = 1.0 + 0.2 * rng.randn(*a.shape)
        elif v.name.endswith(".b_0"):
            nv = 0.1 * rng.randn(*a.shape)
        else:
            nv = 0.35 * rng.randn(*a.shape)
        params[v.name] = nv.astype(a.dtype)

    r2 = np.random.RandomState(3)
    src = np.zeros((slots, maxlen), np.int64)
    src_len = np.ones((slots,), np.int64)
    for j in range(slots):
        n = int(r2.randint(3, maxlen))
        src[j, :n] = r2.randint(2, 60, (n,))
        src_len[j] = n

    def run_arm(kern_on, kv_quant):
        os.environ["PADDLE_TPU_KERN"] = "on" if kern_on else "off"
        stats0 = None
        if kern_on:
            # loaded only for the ON arms — the off arms must witness
            # a pallas-free, registry-free process
            from paddle_tpu.ops.pallas import flash_attention as fa
            fa.set_mode("interpret")
            from paddle_tpu.ops.kern import registry as kreg
            stats0 = {k: dict(v) for k, v
                      in kreg.STATS["by_kernel"].items()}
        dec = tfm.IncrementalDecoder(cfg, params, num_slots=slots,
                                     max_len=maxlen, kv_quant=kv_quant)
        state = dec.write_slots(dec.init_state(),
                                dec.prefill(src, src_len),
                                list(range(slots)))
        ids = np.zeros(slots, np.int64)
        pos = np.zeros(slots, np.int64)
        ids = dec.step(state, ids, pos)           # compile
        toks = [ids.copy()]
        t0 = time.perf_counter()
        for _ in range(1, steps):
            pos = pos + 1
            ids = dec.step(state, ids, pos)
            toks.append(ids.copy())
        step_ms = (time.perf_counter() - t0) / max(steps - 1, 1) * 1e3
        accepted = {}
        if kern_on:
            from paddle_tpu.ops.kern import registry as kreg
            for name, per in kreg.STATS["by_kernel"].items():
                d = per["accepted"] - stats0.get(name, {}).get(
                    "accepted", 0)
                if d:
                    accepted[name] = d
        return {"step_ms": round(step_ms, 2), "toks": toks,
                "accepted": accepted}

    old_kern = os.environ.get("PADDLE_TPU_KERN")
    try:
        # off arms FIRST: while they run, no ops.kern machinery and no
        # ops.pallas module may load (the bench-contract pin, witnessed
        # here too)
        off_fp32 = run_arm(False, None)
        off_int8 = run_arm(False, "int8")
        clean_off = not any(
            m.startswith(("paddle_tpu.ops.pallas",
                          "paddle_tpu.ops.kern.registry"))
            for m in sys.modules)
        on_fp32 = run_arm(True, None)
        on_int8 = run_arm(True, "int8")
    finally:
        fa_mod = sys.modules.get(
            "paddle_tpu.ops.pallas.flash_attention")
        if fa_mod is not None:
            fa_mod.set_mode("auto")
        if old_kern is None:
            os.environ.pop("PADDLE_TPU_KERN", None)
        else:
            os.environ["PADDLE_TPU_KERN"] = old_kern

    def match(a, b):
        return round(float(np.mean([np.array_equal(x, y)
                                    for x, y in zip(a["toks"],
                                                    b["toks"])])), 4)

    fp32_match = match(off_fp32, on_fp32)
    int8_match = match(off_int8, on_int8)
    n_layer = cfg.n_layer
    pass_dispatch = (
        on_fp32["accepted"].get("decode_attend", 0) >= n_layer
        and on_int8["accepted"].get("dequant_attend_int8", 0) >= n_layer
        and on_int8["accepted"].get("int8_quant", 0) >= n_layer)
    total_accepted = sum(on_fp32["accepted"].values()) \
        + sum(on_int8["accepted"].values())
    result = {
        "metric": "kern_registry_accepted_dispatches",
        "value": total_accepted,
        "unit": "kernels dispatched at trace time",
        "platform": "cpu",
        "kern_decode_fp32_off_ms": off_fp32["step_ms"],
        "kern_decode_fp32_on_ms": on_fp32["step_ms"],
        "kern_decode_int8_off_ms": off_int8["step_ms"],
        "kern_decode_int8_on_ms": on_int8["step_ms"],
        "fp32_token_match": fp32_match,
        "int8_token_match": int8_match,
        "accepted_fp32": on_fp32["accepted"],
        "accepted_int8": on_int8["accepted"],
        "registry_off_imported_nothing": clean_off,
        "pass_dispatch": pass_dispatch,
        "pass_parity": fp32_match == 1.0 and int8_match == 1.0,
        "note": ("interpret-mode Pallas on CPU: the on-arm times are "
                 "evidence of dispatch + token parity, not speed; the "
                 "speed A/B needs the chip"),
        "history_stage": "kern",
        "steps": steps, "slots": slots, "maxlen": maxlen,
    }
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_kernels.json")
        with open(path, "w") as f:
            json.dump({"schema": "paddle_tpu.bench.kernels.v1",
                       **result}, f, indent=1)
    except OSError:
        pass
    _append_history(result)
    _emit(result)
    return 0 if (pass_dispatch and result["pass_parity"]) else 1


def main():
    for i, arg in enumerate(sys.argv[1:], start=1):
        if arg.startswith("--deepfm-vocab-rows"):
            _, eq, v = arg.partition("=")
            val = v if eq else (sys.argv[i + 1]
                                if len(sys.argv) > i + 1 else "")
            if val:
                os.environ["BENCH_DEEPFM_VOCAB_ROWS"] = val
    for i, arg in enumerate(sys.argv[1:], start=1):
        if arg.startswith("--grad-sync"):
            _, eq, v = arg.partition("=")
            mode = v if eq else (sys.argv[i + 1]
                                 if len(sys.argv) > i + 1 else "int8")
            sys.exit(_grad_sync_mode(mode=mode or "int8"))
        if arg.startswith("--sparse"):
            _, eq, v = arg.partition("=")
            vocab = int(float(v)) if eq and v else 100_000_000
            sys.exit(_sparse_mode(vocab_rows=vocab))
        if arg.startswith("--async-steps"):
            _, eq, v = arg.partition("=")
            depth = int(v) if eq and v else 4
            sys.exit(_async_mode(k=depth))
        if arg == "--kern":
            sys.exit(_kern_mode())
    if os.environ.get("BENCH_CHILD"):
        _child_main()
    else:
        _Supervisor().run()


if __name__ == "__main__":
    main()
