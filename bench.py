"""Benchmark entry — prints ONE JSON line with the headline metric.

Flagship: train-step throughput on the real chip. Until the Transformer
model lands this measures the MNIST-MLP train step (BASELINE PR1 config);
it will be upgraded to Transformer tokens/sec.
"""
import json
import time

import numpy as np


def main():
    import jax
    fn, (persist, feed, key) = __import__("__graft_entry__").entry()
    jfn = jax.jit(fn, donate_argnums=(0,))
    # warmup/compile
    fetches, persist = jfn(persist, feed, key)
    jax.block_until_ready(fetches)
    n = 50
    t0 = time.perf_counter()
    for i in range(n):
        fetches, persist = jfn(persist, feed, key)
    jax.block_until_ready(fetches)
    dt = time.perf_counter() - t0
    steps_per_sec = n / dt
    samples_per_sec = steps_per_sec * feed["img"].shape[0]

    baseline = None
    try:
        with open("BASELINE.json") as f:
            baseline = json.load(f).get("published", {}).get("samples_per_sec")
    except Exception:
        pass
    vs = samples_per_sec / baseline if baseline else 1.0
    print(json.dumps({
        "metric": "mnist_mlp_train_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/sec",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
