"""ImageNet reader for the benchmark suite.

Parity: benchmark/fluid/imagenet_reader.py — file-list driven train/val
readers with resize-short(256) → 224 crop (random+flip for train,
center for val) → CHW float32 → per-channel mean/std normalization, and
a threaded preprocessing pipeline (the reference uses a hand-rolled
Queue+thread pool; here reader.xmap_readers provides the same shape).

Layout expected under --data_dir (same as the reference):
    train/ train.txt val/ val.txt     ("<relpath> <label>" per line)

Offline stand-in: when the directory is absent or lists are missing,
`train`/`val` fall back to a deterministic synthetic stream with the
exact output spec ([3,224,224] float32 normalized + int label) so the
benchmark CLI stays runnable end-to-end — consistent with
paddle_tpu/dataset's documented synthetic policy.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))  # repo root

from paddle_tpu.dataset import image
from paddle_tpu.reader import xmap_readers

DATA_DIM = 224
RESIZE_DIM = 256
THREAD = int(os.getenv("PREPROCESS_THREADS", "10"))
BUF_SIZE = 1024

img_mean = np.array([0.485, 0.456, 0.406], "float32")
img_std = np.array([0.229, 0.224, 0.225], "float32")


def _normalize(chw):
    chw = chw / 255.0
    chw -= img_mean[:, None, None]
    chw /= img_std[:, None, None]
    return chw


def _mapper(is_train):
    def process(sample):
        path, label = sample
        im = image.load_image(path)
        im = image.simple_transform(im, RESIZE_DIM, DATA_DIM, is_train)
        return _normalize(im), label
    return process


def _file_list(data_dir, list_name, sub_dir):
    entries = []
    with open(os.path.join(data_dir, list_name)) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            parts = line.split()  # whitespace: tabs and spaces both
            if len(parts) != 2:
                raise ValueError(f"bad {list_name} line: {line!r}")
            entries.append((os.path.join(data_dir, sub_dir, parts[0]),
                            int(parts[1])))
    return entries


def _synthetic(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            im = rng.randint(0, 256, (3, DATA_DIM, DATA_DIM))
            yield _normalize(im.astype("float32")), \
                int(rng.randint(0, 1000))
    return reader


def _make(data_dir, list_name, sub_dir, is_train, shuffle, n_synth,
          seed):
    if data_dir is None \
            or not os.path.exists(os.path.join(data_dir, list_name)):
        return _synthetic(n_synth, seed)
    entries = _file_list(data_dir, list_name, sub_dir)
    epoch = [0]

    def raw_reader():
        # reshuffle per PASS with a per-epoch seed: one construction-time
        # shuffle would feed every epoch the identical order (and the
        # same batch composition), quietly hurting convergence —
        # deterministic across runs, different across epochs
        order = list(entries)
        if shuffle:
            np.random.RandomState(seed + epoch[0]).shuffle(order)
            epoch[0] += 1
        return iter(order)

    # eval keeps stream order (stable metrics pairing); train doesn't
    # need it and unordered drains the pool faster
    return xmap_readers(_mapper(is_train), raw_reader,
                        process_num=THREAD, buffer_size=BUF_SIZE,
                        order=not is_train)


def train(data_dir=None, n_synthetic=256):
    """[3,224,224] float32 normalized image + int label, shuffled,
    random-crop + flip augmentation (ref imagenet_reader.py:train)."""
    return _make(data_dir, "train.txt", "train", True, True,
                 n_synthetic, seed=11)


def val(data_dir=None, n_synthetic=64):
    """Center-crop evaluation stream (ref imagenet_reader.py:val)."""
    return _make(data_dir, "val.txt", "val", False, False,
                 n_synthetic, seed=13)


# reference aliases (recordio_converter.py imports these names)
imagenet_train = train
imagenet_test = val
