"""CLI for the fluid benchmark runner.

Parity: benchmark/fluid/args.py — same flag names/defaults so the
reference's run commands work verbatim, with TPU added to --device
(and accepted as the default on this stack). GPU is taken as an alias
of TPU, matching fluid.CUDAPlace -> TPUPlace aliasing.
"""
import argparse

BENCHMARK_MODELS = ["machine_translation", "resnet", "vgg", "mnist",
                    "stacked_dynamic_lstm", "se_resnext"]


def parse_args():
    parser = argparse.ArgumentParser("Fluid model benchmarks.")
    parser.add_argument("--model", type=str, choices=BENCHMARK_MODELS,
                        default="resnet")
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--learning_rate", type=float, default=0.001)
    parser.add_argument("--skip_batch_num", type=int, default=5,
                        help="warmup minibatches excluded from timing")
    parser.add_argument("--iterations", type=int, default=80)
    parser.add_argument("--pass_num", type=int, default=1)
    parser.add_argument("--data_format", type=str, default="NCHW",
                        choices=["NCHW", "NHWC"])
    parser.add_argument("--device", type=str, default="TPU",
                        choices=["CPU", "GPU", "TPU"])
    parser.add_argument("--data_set", type=str, default="cifar10",
                        choices=["cifar10", "flowers", "imagenet"])
    parser.add_argument("--data_dir", type=str, default=None,
                        help="real dataset root (imagenet layout: "
                             "train/ train.txt val/ val.txt); default "
                             "synthetic feeds")
    parser.add_argument("--infer_only", action="store_true")
    parser.add_argument("--use_bf16", action="store_true",
                        help="bf16 AMP (replaces the reference's fp16)")
    parser.add_argument("--profile", action="store_true",
                        help="device-side per-op profile of the steady "
                             "state (jax.profiler xplane)")
    return parser.parse_args()
