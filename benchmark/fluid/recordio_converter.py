"""Dataset → RecordIO converter for the benchmark suite.

Parity: benchmark/fluid/recordio_converter.py — prepare_mnist /
prepare_cifar10 / prepare_flowers batch a dataset reader through a
DataFeeder and write `.recordio` shards the benchmark's reader-op path
(and the native sharded C++ reader, native/recordio_multi.cc) can
stream. Same flow here over the repo's own pieces: dataset readers →
paddle_tpu.batch → DataFeeder → recordio_writer.

CLI:
  python recordio_converter.py --dataset mnist --out /tmp/rio \
      --batch_size 32 [--batch_per_file 64]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))  # repo root

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.dataset import cifar, flowers, mnist
from paddle_tpu.reader import batch as batch_reader
from paddle_tpu.recordio_writer import (
    convert_reader_to_recordio_file, convert_reader_to_recordio_files)


def convert_2_recordio(py_reader, outfilepath, batch_size, shape_data,
                       shape_label, batch_per_file=None):
    """ref recordio_converter.py:convert_2_recordio — returns the
    number of records (batches) written."""
    with fluid.program_guard(fluid.Program(), fluid.Program()):
        reader = batch_reader(py_reader(), batch_size=batch_size)
        feeder = fluid.DataFeeder(
            feed_list=[
                layers.data(name="image", shape=shape_data),
                layers.data(name="label", shape=shape_label,
                            dtype="int64"),
            ],
            place=fluid.CPUPlace())
        feed_reader = lambda: map(feeder.feed, reader())
        if batch_per_file:
            paths = convert_reader_to_recordio_files(
                outfilepath, batch_per_file, feed_reader, feeder)
            return len(paths)
        return convert_reader_to_recordio_file(outfilepath, feed_reader,
                                               feeder)


def prepare_mnist(outpath, batch_size, **kw):
    out = os.path.join(outpath, "mnist.recordio")
    return convert_2_recordio(mnist.train, out, batch_size, [784], [1],
                              **kw)


def prepare_cifar10(outpath, batch_size, **kw):
    out = os.path.join(outpath, "cifar.recordio")
    return convert_2_recordio(cifar.train10, out, batch_size,
                              [3, 32, 32], [1], **kw)


def prepare_flowers(outpath, batch_size, **kw):
    out = os.path.join(outpath, "flowers.recordio")
    return convert_2_recordio(flowers.train, out, batch_size,
                              [3, 224, 224], [1], **kw)


PREPARE = {"mnist": prepare_mnist, "cifar10": prepare_cifar10,
           "flowers": prepare_flowers}


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dataset", choices=sorted(PREPARE), default="mnist")
    p.add_argument("--out", required=True)
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--batch_per_file", type=int, default=None,
                   help="shard into files of N batches (sharded "
                        "multithreaded reader input)")
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)
    n = PREPARE[args.dataset](args.out, args.batch_size,
                              batch_per_file=args.batch_per_file)
    print(f"wrote {n} {'files' if args.batch_per_file else 'records'} "
          f"to {args.out}")


if __name__ == "__main__":
    main()
