"""Transformer NMT benchmark model (parity:
benchmark/fluid/models/machine_translation.py — the reference's
headline seq2seq benchmark, here the transformer-base from the zoo)."""
import numpy as np

from paddle_tpu.models import transformer as zoo


def get_model(args):
    T = 128
    cfg = zoo.TransformerConfig(src_vocab=8000, trg_vocab=8000,
                                max_len=T, d_model=512, d_inner=2048,
                                n_head=8, n_layer=6, dropout=0.1)
    feeds, avg_cost, tok = zoo.build_program(cfg, maxlen=T,
                                             use_noam=False)

    def feed_fn(batch_size, rng):
        src = rng.randint(3, cfg.src_vocab, (batch_size, T)).astype(
            "int32")
        trg = np.concatenate(
            [np.zeros((batch_size, 1), "int32"),
             (src[:, :-1] + 1) % cfg.trg_vocab], axis=1)
        return {"src": src,
                "src_len": np.full(batch_size, T, "int32"),
                "trg": trg,
                "trg_len": np.full(batch_size, T, "int32"),
                "label": ((src + 1) % cfg.trg_vocab).astype("int32")}

    return avg_cost, feed_fn
