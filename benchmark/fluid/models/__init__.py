"""Benchmark model builders (parity: benchmark/fluid/models/__init__.py).

Each module exposes get_model(args) -> (loss_var, feed_fn) where
feed_fn(batch_size, rng) returns a ready feed dict of synthetic data
with the reference benchmark's shapes.
"""
__all__ = ["machine_translation", "resnet", "vgg", "mnist",
           "stacked_dynamic_lstm", "se_resnext"]

# dataset input sizes / class counts shared by the vision models
DATA_HW = {"cifar10": 32, "flowers": 224, "imagenet": 224}
DATA_CLASSES = {"cifar10": 10, "flowers": 102, "imagenet": 1000}
