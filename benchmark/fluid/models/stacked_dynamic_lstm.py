"""Stacked LSTM benchmark model (parity:
benchmark/fluid/models/stacked_dynamic_lstm.py — variable-length
sentiment LM; lengths ride the seq_len vector, shapes stay static)."""
from paddle_tpu.models import stacked_lstm as zoo

_T = 128
_DICT = 5147


def get_model(args):
    feeds, avg_cost, acc = zoo.build_program(dict_dim=_DICT, maxlen=_T)

    def feed_fn(batch_size, rng):
        lens = rng.randint(_T // 2, _T + 1, batch_size)
        words = rng.randint(0, _DICT, (batch_size, _T))
        for i, l in enumerate(lens):
            words[i, l:] = 0
        return {"words": words.astype("int64"),
                "words_seq_len": lens.astype("int64"),
                "label": rng.randint(0, 2, (batch_size, 1))}

    return avg_cost, feed_fn
