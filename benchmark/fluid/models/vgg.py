"""VGG-16 benchmark model (parity: benchmark/fluid/models/vgg.py)."""
from paddle_tpu import layers
from paddle_tpu.models import vgg as zoo

from . import DATA_HW, DATA_CLASSES


def get_model(args):
    hw = DATA_HW[args.data_set]
    classes = DATA_CLASSES[args.data_set]
    img = layers.data("data", shape=[3, hw, hw])
    label = layers.data("label", shape=[1], dtype="int64")
    predict = zoo.vgg16(img, class_dim=classes)
    loss = layers.mean(layers.cross_entropy(input=predict, label=label))

    def feed_fn(batch_size, rng):
        return {"data": rng.rand(batch_size, 3, hw, hw).astype("float32"),
                "label": rng.randint(0, classes, (batch_size, 1))}

    return loss, feed_fn
