"""ResNet-50 benchmark model (parity: benchmark/fluid/models/resnet.py)."""
from paddle_tpu import layers
from paddle_tpu.models import resnet as zoo

from . import DATA_HW, DATA_CLASSES


def get_model(args):
    hw = DATA_HW[args.data_set]
    classes = DATA_CLASSES[args.data_set]
    img = layers.data("data", shape=[3, hw, hw])
    label = layers.data("label", shape=[1], dtype="int64")
    # ImageNet-sized inputs run the 50-layer net; 32x32 runs 18 layers
    predict = zoo.resnet(img, class_dim=classes,
                         depth=50 if hw == 224 else 18)
    loss = layers.mean(layers.cross_entropy(input=predict, label=label))

    def feed_fn(batch_size, rng):
        return {"data": rng.rand(batch_size, 3, hw, hw).astype("float32"),
                "label": rng.randint(0, classes, (batch_size, 1))}

    return loss, feed_fn
