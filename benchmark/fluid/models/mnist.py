"""MNIST MLP benchmark model (parity: benchmark/fluid/models/mnist.py)."""
from paddle_tpu import layers
from paddle_tpu.models import mnist as zoo


def get_model(args):
    img = layers.data("pixel", shape=[784])
    label = layers.data("label", shape=[1], dtype="int64")
    predict = zoo.mlp(img)
    loss = layers.mean(layers.cross_entropy(input=predict, label=label))

    def feed_fn(batch_size, rng):
        return {"pixel": rng.rand(batch_size, 784).astype("float32"),
                "label": rng.randint(0, 10, (batch_size, 1))}

    return loss, feed_fn
