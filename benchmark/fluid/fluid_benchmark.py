"""Fluid benchmark runner.

Parity: benchmark/fluid/fluid_benchmark.py — same CLI, same report
(samples/sec over the timed iterations, warmup skipped), re-designed
for the TPU stack: the whole train step (fwd+bwd+update) compiles to
ONE XLA module via the tracing Executor; --device TPU runs on the real
chip, CPU forces the host backend (GPU is accepted as a TPU alias).

Examples:
  python fluid_benchmark.py --model mnist --device CPU --iterations 20
  python fluid_benchmark.py --model machine_translation --batch_size 64 \
      --use_bf16 --iterations 40
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))  # repo root

from args import parse_args


def main():
    args = parse_args()
    if args.data_format == "NHWC":
        raise ValueError("only NCHW is supported (same as the reference)")
    if args.device == "CPU":
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as fluid

    model_mod = __import__(f"models.{args.model}",
                           fromlist=["get_model"])
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        with fluid.unique_name.guard():
            loss, feed_fn = model_mod.get_model(args)
            opt = fluid.optimizer.Adam(args.learning_rate) \
                if args.model == "machine_translation" \
                else fluid.optimizer.Momentum(args.learning_rate, 0.9)
            if not args.infer_only:
                opt.minimize(loss)
    if args.use_bf16:
        fluid.amp.cast_program_to_bf16(main_p)

    place = fluid.CPUPlace() if args.device == "CPU" \
        else fluid.TPUPlace(0)
    exe = fluid.Executor(place)
    exe.run(startup_p)
    if args.use_bf16:
        fluid.amp.cast_params_to_bf16(main_p, fluid.global_scope())

    rng = np.random.RandomState(0)
    total = args.skip_batch_num + args.iterations
    losses, t0 = [], None
    prog = main_p.clone(for_test=True) if args.infer_only else main_p
    next_feed = lambda: feed_fn(args.batch_size, rng)
    if args.data_set == "imagenet" and args.data_dir:
        # real-data path: stream + preprocess through the threaded
        # imagenet reader instead of synthetic feeds
        import imagenet_reader
        from paddle_tpu.reader import batch as batch_reader
        _batched = batch_reader(imagenet_reader.train(args.data_dir),
                                batch_size=args.batch_size)
        _stream = [_batched()]

        def next_feed():
            # cycle the reader across epochs — a benchmark run is
            # allowed to outlast one pass over the data
            try:
                samples = next(_stream[0])
            except StopIteration:
                _stream[0] = _batched()
                samples = next(_stream[0])
            imgs, labels = zip(*samples)
            return {"data": np.stack(imgs).astype("float32"),
                    "label": np.asarray(labels).reshape(-1, 1)}
    for p in range(args.pass_num):
        for it in range(total):
            if it == args.skip_batch_num:
                t0 = time.perf_counter()
            out = exe.run(prog, feed=next_feed(),
                          fetch_list=[loss])
            losses.append(float(np.asarray(out[0])))
        dt = time.perf_counter() - t0
        sps = args.iterations * args.batch_size / dt
        print(f"Pass: {p}, Loss: {losses[-1]:.5f}, "
              f"Speed: {sps:.2f} samples/s "
              f"({dt / args.iterations * 1e3:.2f} ms/iter)")
    if args.profile:
        from paddle_tpu.profiler import profile_step_fn
        feed = feed_fn(args.batch_size, rng)

        def one_step():
            return exe.run(prog, feed=feed, fetch_list=[loss])

        dev_s, fams = profile_step_fn(one_step, steps=10)
        top = sorted(fams.items(), key=lambda kv: -kv[1])[:8]
        print(f"device step: {dev_s * 1e3:.2f} ms; top op families:")
        for k, v in top:
            print(f"  {k:<28} {v * 1e3:8.2f} ms")
    assert all(np.isfinite(losses)), "non-finite loss"


if __name__ == "__main__":
    main()
